"""DataIterator + streaming split.

Reference: `data/iterator.py` DataIterator and
`Dataset.streaming_split` — N concurrent consumers (Train workers)
each pull blocks from one shared streaming execution.  A coordinator
actor owns the execution generator; shards pull blocks
first-come-first-served, which load-balances uneven consumers (the
reference's output-splitter operator behaves the same way for
equal=False).

Delivery protocol (elastic ingest, ROADMAP item 1): every delivered
block carries a sequence number and stays "outstanding" until the
consumer ACKNOWLEDGES it.  Acks are ROW-EXACT and flushed once per
emitted batch, immediately before the batch is yielded: blocks whose
rows have fully left the rebatcher commit as consumed, and the
straddling block commits a row offset — so a consumer that unwinds
cleanly at a batch boundary (the elastic drain) has exactly its
emitted rows committed for ANY batch_size, and redelivery resumes
MID-block past the committed offset.  When the training mesh
shrinks/re-grows mid-epoch, `reshard(m)` requeues every outstanding
block (at its committed offset) for redelivery, bumps a generation
token that fences stale consumers, and resizes the shard set WITHOUT
restarting the epoch: committed rows are never redelivered and
uncommitted rows are never dropped — exactly-once ingest across the
transition.  A generator failure (e.g. a read task out of retries) is
recorded and re-raised to EVERY shard — unrecoverable loss is a typed
error at each consumer, never a silent partial epoch or a hang.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from ray_tpu.data import block as B

logger = logging.getLogger(__name__)


def rebatch(
    blocks: Iterator[B.Block],
    *,
    batch_size: Optional[int],
    batch_format: str = "numpy",
    drop_last: bool = False,
) -> Iterator[Any]:
    carry: Optional[B.Block] = None
    for blk in blocks:
        carry = blk if carry is None else B.concat([carry, blk])
        if batch_size is None:
            if B.num_rows(carry):
                yield B.format_batch(carry, batch_format)
            carry = None
            continue
        while carry is not None and B.num_rows(carry) >= batch_size:
            yield B.format_batch(B.slice_block(carry, 0, batch_size), batch_format)
            rest = B.slice_block(carry, batch_size, B.num_rows(carry))
            carry = rest if B.num_rows(rest) else None
    if carry is not None and B.num_rows(carry) and not drop_last:
        yield B.format_batch(carry, batch_format)


def shuffle_buffer(
    blocks: Iterator[B.Block], buffer_size: int, seed: Optional[int] = None
) -> Iterator[B.Block]:
    """Moving-window shuffle: accumulate rows into a buffer; once it
    holds >= buffer_size rows, emit a random half and keep refilling —
    rows mix ACROSS block boundaries up to the buffer size (reference:
    iter_batches local_shuffle_buffer_size semantics)."""
    rng = np.random.default_rng(seed)
    buf: Optional[B.Block] = None
    for blk in blocks:
        buf = blk if buf is None else B.concat([buf, blk])
        n = B.num_rows(buf)
        while n >= buffer_size:
            perm = rng.permutation(n)
            emit = max(1, n - buffer_size // 2)
            yield B.take_indices(buf, perm[:emit])
            buf = B.take_indices(buf, perm[emit:])
            n = B.num_rows(buf)
    if buf is not None and B.num_rows(buf):
        yield B.take_indices(buf, rng.permutation(B.num_rows(buf)))


class _SplitCoordinator:
    """Owns one streaming execution per epoch; shards pull blocks.

    The generator is only replaced once the current one is EXHAUSTED —
    a shard asks for epoch N+1 only after it drained epoch N (got None),
    and None implies exhaustion, so a fast shard looping around can
    never truncate a slow shard's in-progress epoch.  "Exhausted" means
    the generator is done AND the redelivery queue is drained: blocks
    requeued by a reshard are still owed to the epoch.
    """

    def __init__(self, dataset, n: int, equal: bool = False,
                 data_context=None):
        import threading
        from collections import deque as _dq

        if data_context is not None:
            # the driver's DataContext (retry depth, backpressure
            # budgets) governs the execution it coordinates, not the
            # defaults of whatever worker process this actor landed in
            # (reference: DataContext propagation to execution workers)
            from ray_tpu.data import context as _ctx_mod

            _ctx_mod._current_context = data_context
        self._dataset = dataset
        self._n = n
        self._equal = equal
        self._epoch = -1
        self._gen = None
        self._done = True
        self._error: Optional[BaseException] = None
        # SYNC methods + threading primitives: methods run in executor
        # threads (max_concurrency sizes the pool), where blocking
        # rt.get/rt.put are safe — an async coordinator would run on the
        # runtime's io loop and deadlock on them.  ONE reentrant mutex
        # backs both the lock and the condition: epoch rollover mutates
        # several fields, and readers must never observe a half-applied
        # transition (nor can two lock orders deadlock).
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._queues = [_dq() for _ in range(n)]  # equal-mode shards
        self._carry = None  # remainder rows carried between blocks
        #: equal-mode backpressure: a shard pulling new blocks waits
        #: while any sibling's queue is this deep (the reference output
        #: splitter blocks when a consumer lags)
        self._max_queued = 16
        # -- exactly-once delivery state --------------------------------
        self._seq = 0  # next delivery sequence number
        #: reshard generation: bumped on every reshard; pulls/acks from
        #: iterators of an older generation are fenced (stale consumers
        #: stop cleanly instead of racing the new shard set)
        self._gen_id = 0
        #: seq -> [pair, base_offset, rows_consumed]: delivered but not
        #: fully acknowledged.  base_offset is how many rows of the
        #: underlying block were consumed BEFORE this delivery (a
        #: redelivered block resumes mid-block); rows_consumed advances
        #: with partial acks as the consumer emits batches.  Requeued on
        #: reshard at (base_offset + rows_consumed).
        self._outstanding: Dict[int, list] = {}
        #: (orig_seq, pair, offset) owed to the CURRENT epoch after a
        #: reshard; orig_seq lets a late in-flight ack retract an entry
        #: before it is redelivered
        self._redeliver = _dq()

    # -- lifecycle -----------------------------------------------------
    def attach(self):
        """State snapshot for late-joining consumers (elastic re-form):
        (current_epoch, in_progress, generation).  `in_progress` counts
        undelivered redelivery/queue debt: a generator that exhausted
        with blocks still owed is NOT a finished epoch."""
        with self._lock:
            in_progress = (
                not self._done
                or bool(self._redeliver)
                or any(self._queues)
            )
            return self._epoch, in_progress, self._gen_id

    def reshard(self, n: int):
        """Re-shard the in-progress epoch to `n` consumers (mesh
        shrink/re-grow).  Delivered-but-unacked blocks — in flight to
        consumers that may be dead — are requeued for redelivery;
        acked blocks are gone for good; queued equal-mode sub-blocks
        are folded back into the redelivery pool.  The epoch itself is
        NOT restarted."""
        from collections import deque as _dq

        with self._cond:
            self._gen_id += 1  # fence pulls/acks from prior consumers
            requeued = 0
            for seq in sorted(self._outstanding):
                pair, base, used = self._outstanding[seq]
                self._redeliver.append((seq, pair, base, used))
                requeued += 1
            self._outstanding.clear()
            for q in self._queues:
                while q:
                    self._redeliver.append((-1, q.popleft(), 0, 0))
                    requeued += 1
            self._n = n
            self._queues = [_dq() for _ in range(n)]
            self._cond.notify_all()
            logger.info(
                "split coordinator resharded to %d shards "
                "(requeued %d in-flight blocks, epoch %d, gen %d)",
                n, requeued, self._epoch, self._gen_id,
            )
            return {"epoch": self._epoch, "requeued": requeued,
                    "gen": self._gen_id}

    def ack(self, shard: int, epoch: int, gen: int, full_seqs,
            partial=None) -> bool:
        """Consumption commit, flushed once per emitted batch:
        `full_seqs` blocks are fully consumed (never redelivered);
        `partial` is (seq, rows) — the straddling block's consumed-row
        offset, so redelivery after a loss resumes MID-block and the
        exactly-once ledger is row-exact for any batch_size.  An ack
        from a pre-reshard generation retracts the matching entries
        from the redelivery queue when they have not been handed out
        yet (the in-flight-ack race closes in the consumer's favor)."""
        with self._cond:
            if epoch != self._epoch:
                return True
            if gen == self._gen_id or gen is None:
                for seq in full_seqs:
                    self._outstanding.pop(seq, None)
                if partial is not None:
                    ent = self._outstanding.get(partial[0])
                    if ent is not None:
                        ent[2] = max(ent[2], int(partial[1]))
            else:
                # partial[1] is relative to the DELIVERED view (rows
                # past the entry's base offset) — compose with base,
                # never clobber it, or a twice-resharded block loses
                # its first redelivery's committed rows
                retract = set(full_seqs)
                keep = type(self._redeliver)()
                for oseq, pair, base, used in self._redeliver:
                    if oseq in retract:
                        continue
                    if partial is not None and oseq == partial[0]:
                        used = max(used, int(partial[1]))
                    keep.append((oseq, pair, base, used))
                self._redeliver = keep
            self._cond.notify_all()
        return True

    def start_epoch(self, shard: int, epoch: int) -> bool:
        with self._cond:
            if epoch <= self._epoch:
                return True
            # wait for exhaustion (only reachable if a caller skips
            # ahead without draining; normal iterators never wait here)
            self._cond.wait_for(
                lambda: self._done
                and not self._redeliver
                and all(not q for q in self._queues)
            )
            if epoch > self._epoch:
                self._epoch = epoch
                self._gen = self._dataset._pairs()
                self._done = False
                self._error = None
                self._queues = [type(self._queues[0])() for _ in range(self._n)]
                self._carry = None
                # epoch rollover: delivered-but-unacked debt from the
                # PREVIOUS epoch is void (that epoch's consumers are
                # gone; the new epoch redelivers everything anyway)
                self._outstanding.clear()
                self._redeliver.clear()
        return True

    def _next_upstream(self):
        """One (pair, offset) from the redelivery pool or the generator
        (callers hold the lock).  Raises the recorded generator error,
        marks done on exhaustion (returns None)."""
        if self._redeliver:
            _oseq, pair, base, used = self._redeliver.popleft()
            return pair, base + used
        if self._error is not None:
            raise self._error
        if self._done:
            return None
        try:
            return next(self._gen), 0
        except StopIteration:
            self._mark_done()
            return None
        except Exception as e:
            # an unrecoverable upstream loss (task out of retries,
            # lineage gone): record it so EVERY shard surfaces the
            # same typed error instead of a silent partial epoch
            self._error = e
            self._mark_done()
            raise

    def _deliver(self, pair, offset=0):
        """Stamp a delivery sequence number and track it until acked."""
        seq = self._seq
        self._seq += 1
        self._outstanding[seq] = [pair, offset, 0]
        return (seq, pair, offset)

    def next_block(self, shard: int, epoch: int, gen: int = None):
        if epoch != self._epoch:
            return None
        if not self._equal:
            with self._lock:
                # re-check under the lock: a shard parked here across
                # an epoch rollover must not pull from the NEW epoch's
                # generator for its stale epoch-N call; a pre-reshard
                # iterator (stale generation) sees a clean end instead
                # of racing the new shard set for the generator
                if epoch != self._epoch or (
                    gen is not None and gen != self._gen_id
                ):
                    return None
                item = self._next_upstream()
                if item is None:
                    return None
                return self._deliver(*item)
        # equal=True: every shard receives exactly the same row count
        # (reference: the output splitter's equal mode).  Each upstream
        # block (plus carried remainder) splits into n equal sub-blocks
        # pushed one per shard queue; remainder rows carry into the next
        # block and only the final < n rows are dropped at exhaustion.
        import ray_tpu as rt

        with self._lock:
            if epoch != self._epoch or (
                gen is not None and gen != self._gen_id
            ):  # rolled over / resharded while parked at the lock
                return None
            while not self._queues[shard]:
                if self._error is not None:
                    # surface the recorded upstream failure to EVERY
                    # shard, not just the one that tripped it
                    raise self._error
                if self._done and not self._redeliver:
                    return None
                # soft backpressure: while a lagging sibling's queue is
                # deep, this shard pauses driving the upstream generator
                # — but boundedly, so a shard whose consumer drains the
                # split sequentially (no concurrent siblings) still
                # progresses instead of deadlocking
                waited = 0.0
                while (
                    any(len(q) >= self._max_queued for q in self._queues)
                    and waited < 5.0
                    and not self._done
                ):
                    self._cond.wait(timeout=0.5)
                    waited += 0.5
                    if epoch != self._epoch or (
                        gen is not None and gen != self._gen_id
                    ):  # rolled over / resharded while parked
                        return None
                if self._done and not self._redeliver:
                    continue  # loop re-checks queue/done/error
                item = self._next_upstream()
                if item is None:
                    continue
                (block_ref, _meta), up_off = item
                try:
                    blk = rt.get(block_ref)
                    if up_off:
                        # redelivered block: resume past committed rows
                        blk = B.slice_block(blk, up_off, B.num_rows(blk))
                    if self._carry is not None:
                        blk = B.concat([self._carry, blk])
                        self._carry = None
                    rows = B.num_rows(blk)
                    per = rows // self._n
                    if per == 0:
                        self._carry = blk
                        continue
                    for i in range(self._n):
                        piece = B.slice_block(blk, i * per, (i + 1) * per)
                        meta = {
                            "num_rows": per,
                            "size_bytes": B.size_bytes(piece),
                        }
                        self._queues[i].append((rt.put(piece), meta))
                    rem = rows - per * self._n
                    if rem:
                        self._carry = B.slice_block(blk, rows - rem, rows)
                except Exception as e:
                    # a block value this split could not fetch/split
                    # (reconstruction exhausted, store loss): record it
                    # so every OTHER shard raises too instead of ending
                    # a silently short epoch
                    self._error = e
                    self._mark_done()
                    raise
            out = self._deliver(self._queues[shard].popleft())
            # wake backpressured pullers and epoch-restart waiters (the
            # condition shares this lock, so this is race-free here)
            self._cond.notify_all()
            return out

    def _mark_done(self):
        with self._cond:
            self._done = True
            self._cond.notify_all()


def _batch_rows(batch) -> int:
    """Row count of a formatted batch (numpy dict / arrow / pandas)."""
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return len(batch)
    except ImportError:
        pass
    return B.num_rows(batch)


class DataIterator:
    """Per-shard handle (reference: `data/iterator.py` DataIterator)."""

    def __init__(self, coordinator, index: int, world: int,
                 start_epoch: int = 0, gen: int = 0):
        self._coord = coordinator
        self._index = index
        self._world = world
        self._gen = gen  # reshard generation this iterator belongs to
        # first iter_batches() call runs `start_epoch`: an iterator
        # attached to an in-progress epoch (elastic re-form) CONTINUES
        # it instead of truncating/restarting it
        self._epoch = start_epoch - 1

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        **_kwargs,
    ) -> Iterator[Any]:
        import ray_tpu as rt

        self._epoch += 1
        epoch = self._epoch
        gen = self._gen
        rt.get(self._coord.start_epoch.remote(self._index, epoch))

        # Row-exact consumption ledger: pulled blocks queue here until
        # their rows have been EMITTED as batches; one ack RPC flushes
        # per batch, committing fully-emitted blocks plus the
        # straddling block's row offset.  The flush runs BEFORE each
        # yield, so a consumer that unwinds cleanly at a batch
        # boundary (the elastic drain: report() raises at the step
        # barrier) has exactly its emitted rows committed — rebatch's
        # carry rows stay uncommitted and are redelivered, mid-block
        # if necessary.  A consumer SIGKILLed between flush and
        # processing loses at most the one in-flight batch.
        pulled: List[list] = []  # [seq, rows] in delivery order
        acked_rows = 0
        emitted = 0

        def blocks() -> Iterator[B.Block]:
            while True:
                item = rt.get(self._coord.next_block.remote(
                    self._index, epoch, gen
                ))
                if item is None:
                    return
                seq, (block_ref, _meta), off = item
                blk = rt.get(block_ref)
                n = B.num_rows(blk)
                if off:
                    # redelivered block: resume past its committed rows
                    blk = B.slice_block(blk, off, n)
                    n -= off
                if n <= 0:
                    rt.get(self._coord.ack.remote(
                        self._index, epoch, gen, [seq], None
                    ))
                    continue
                pulled.append([seq, n])
                yield blk

        def flush():
            nonlocal acked_rows
            full = []
            while pulled and acked_rows + pulled[0][1] <= emitted:
                seq, n = pulled.pop(0)
                acked_rows += n
                full.append(seq)
            partial = None
            if pulled and emitted > acked_rows:
                partial = (pulled[0][0], emitted - acked_rows)
            if full or partial:
                rt.get(self._coord.ack.remote(
                    self._index, epoch, gen, full, partial
                ))

        for batch in rebatch(
            blocks(),
            batch_size=batch_size,
            batch_format=batch_format,
            drop_last=drop_last,
        ):
            emitted += _batch_rows(batch)
            flush()
            yield batch

    def iter_rows(self) -> Iterator[Dict]:
        for batch in self.iter_batches(batch_size=None):
            yield from B.iter_rows(batch)

    def iter_jax_batches(self, *, batch_size: int = 256, sharding=None,
                         dtype=None, drop_last: bool = True) -> Iterator[Any]:
        import jax
        import jax.numpy as jnp

        for batch in self.iter_batches(batch_size=batch_size, drop_last=drop_last):
            arrs = {
                k: (jnp.asarray(v, dtype=dtype) if dtype else jnp.asarray(v))
                for k, v in batch.items()
            }
            if sharding is not None:
                arrs = {k: jax.device_put(v, sharding) for k, v in arrs.items()}
            yield arrs


def make_streaming_split(dataset, n: int, *, equal: bool = False,
                         elastic: bool = False) -> List[DataIterator]:
    import ray_tpu as rt

    if elastic:
        cached = getattr(dataset, "_split_coord", None)
        if cached is not None:
            coord, c_equal = cached
            if c_equal == equal:
                try:
                    state = rt.get(coord.reshard.remote(n))
                    epoch, in_progress, gen = rt.get(coord.attach.remote())
                    start = epoch if in_progress else epoch + 1
                    return [DataIterator(coord, i, n, start_epoch=start,
                                         gen=state["gen"])
                            for i in range(n)]
                except Exception as e:
                    # coordinator actor died (its host was lost): fall
                    # through to a fresh one — the epoch restarts, which
                    # is the best recoverable outcome without its state
                    logger.warning(
                        "elastic split coordinator unreachable (%s); "
                        "starting a fresh one", e,
                    )
            dataset._split_coord = None

    from ray_tpu.data.context import DataContext

    coord = rt.remote(_SplitCoordinator).options(
        num_cpus=0, max_concurrency=max(4, 2 * n + 1)
    ).remote(dataset, n, equal, DataContext.get_current())
    if elastic:
        dataset._split_coord = (coord, equal)
    return [DataIterator(coord, i, n) for i in range(n)]
