"""DataIterator + streaming split.

Reference: `data/iterator.py` DataIterator and
`Dataset.streaming_split` — N concurrent consumers (Train workers)
each pull blocks from one shared streaming execution.  A coordinator
actor owns the execution generator; shards pull blocks
first-come-first-served, which load-balances uneven consumers (the
reference's output-splitter operator behaves the same way for
equal=False).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from ray_tpu.data import block as B


def rebatch(
    blocks: Iterator[B.Block],
    *,
    batch_size: Optional[int],
    batch_format: str = "numpy",
    drop_last: bool = False,
) -> Iterator[Any]:
    carry: Optional[B.Block] = None
    for blk in blocks:
        carry = blk if carry is None else B.concat([carry, blk])
        if batch_size is None:
            if B.num_rows(carry):
                yield B.format_batch(carry, batch_format)
            carry = None
            continue
        while carry is not None and B.num_rows(carry) >= batch_size:
            yield B.format_batch(B.slice_block(carry, 0, batch_size), batch_format)
            rest = B.slice_block(carry, batch_size, B.num_rows(carry))
            carry = rest if B.num_rows(rest) else None
    if carry is not None and B.num_rows(carry) and not drop_last:
        yield B.format_batch(carry, batch_format)


def shuffle_buffer(
    blocks: Iterator[B.Block], buffer_size: int, seed: Optional[int] = None
) -> Iterator[B.Block]:
    """Moving-window shuffle: accumulate rows into a buffer; once it
    holds >= buffer_size rows, emit a random half and keep refilling —
    rows mix ACROSS block boundaries up to the buffer size (reference:
    iter_batches local_shuffle_buffer_size semantics)."""
    rng = np.random.default_rng(seed)
    buf: Optional[B.Block] = None
    for blk in blocks:
        buf = blk if buf is None else B.concat([buf, blk])
        n = B.num_rows(buf)
        while n >= buffer_size:
            perm = rng.permutation(n)
            emit = max(1, n - buffer_size // 2)
            yield B.take_indices(buf, perm[:emit])
            buf = B.take_indices(buf, perm[emit:])
            n = B.num_rows(buf)
    if buf is not None and B.num_rows(buf):
        yield B.take_indices(buf, rng.permutation(B.num_rows(buf)))


class _SplitCoordinator:
    """Owns one streaming execution per epoch; shards pull blocks.

    The generator is only replaced once the current one is EXHAUSTED —
    a shard asks for epoch N+1 only after it drained epoch N (got None),
    and None implies exhaustion, so a fast shard looping around can
    never truncate a slow shard's in-progress epoch.
    """

    def __init__(self, dataset, n: int, equal: bool = False):
        import threading
        from collections import deque as _dq

        self._dataset = dataset
        self._n = n
        self._equal = equal
        self._epoch = -1
        self._gen = None
        self._done = True
        # SYNC methods + threading primitives: methods run in executor
        # threads (max_concurrency sizes the pool), where blocking
        # rt.get/rt.put are safe — an async coordinator would run on the
        # runtime's io loop and deadlock on them.  ONE reentrant mutex
        # backs both the lock and the condition: epoch rollover mutates
        # several fields, and readers must never observe a half-applied
        # transition (nor can two lock orders deadlock).
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._queues = [_dq() for _ in range(n)]  # equal-mode shards
        self._carry = None  # remainder rows carried between blocks
        #: equal-mode backpressure: a shard pulling new blocks waits
        #: while any sibling's queue is this deep (the reference output
        #: splitter blocks when a consumer lags)
        self._max_queued = 16

    def start_epoch(self, shard: int, epoch: int) -> bool:
        with self._cond:
            if epoch <= self._epoch:
                return True
            # wait for exhaustion (only reachable if a caller skips
            # ahead without draining; normal iterators never wait here)
            self._cond.wait_for(
                lambda: self._done and all(not q for q in self._queues)
            )
            if epoch > self._epoch:
                self._epoch = epoch
                self._gen = self._dataset._pairs()
                self._done = False
                self._queues = [type(self._queues[0])() for _ in range(self._n)]
                self._carry = None
        return True

    def next_block(self, shard: int, epoch: int):
        if epoch != self._epoch or self._gen is None:
            return None
        if not self._equal:
            with self._lock:
                # re-check under the lock: a shard parked here across
                # an epoch rollover must not pull from the NEW epoch's
                # generator for its stale epoch-N call
                if epoch != self._epoch or self._done:
                    return None
                try:
                    return next(self._gen)
                except StopIteration:
                    self._mark_done()
                    return None
        # equal=True: every shard receives exactly the same row count
        # (reference: the output splitter's equal mode).  Each upstream
        # block (plus carried remainder) splits into n equal sub-blocks
        # pushed one per shard queue; remainder rows carry into the next
        # block and only the final < n rows are dropped at exhaustion.
        import ray_tpu as rt

        with self._lock:
            if epoch != self._epoch:  # rolled over while parked at lock
                return None
            while not self._queues[shard]:
                if self._done:
                    return None
                # soft backpressure: while a lagging sibling's queue is
                # deep, this shard pauses driving the upstream generator
                # — but boundedly, so a shard whose consumer drains the
                # split sequentially (no concurrent siblings) still
                # progresses instead of deadlocking
                waited = 0.0
                while (
                    any(len(q) >= self._max_queued for q in self._queues)
                    and waited < 5.0
                    and not self._done
                ):
                    self._cond.wait(timeout=0.5)
                    waited += 0.5
                    if epoch != self._epoch:
                        return None
                if self._done:
                    continue  # loop re-checks queue/done
                try:
                    block_ref, _meta = next(self._gen)
                except StopIteration:
                    self._mark_done()
                    return None
                blk = rt.get(block_ref)
                if self._carry is not None:
                    blk = B.concat([self._carry, blk])
                    self._carry = None
                rows = B.num_rows(blk)
                per = rows // self._n
                if per == 0:
                    self._carry = blk
                    continue
                for i in range(self._n):
                    piece = B.slice_block(blk, i * per, (i + 1) * per)
                    meta = {
                        "num_rows": per,
                        "size_bytes": B.size_bytes(piece),
                    }
                    self._queues[i].append((rt.put(piece), meta))
                rem = rows - per * self._n
                if rem:
                    self._carry = B.slice_block(blk, rows - rem, rows)
            out = self._queues[shard].popleft()
            # wake backpressured pullers and epoch-restart waiters (the
            # condition shares this lock, so this is race-free here)
            self._cond.notify_all()
            return out

    def _mark_done(self):
        with self._cond:
            self._done = True
            self._cond.notify_all()


class DataIterator:
    """Per-shard handle (reference: `data/iterator.py` DataIterator)."""

    def __init__(self, coordinator, index: int, world: int):
        self._coord = coordinator
        self._index = index
        self._world = world
        self._epoch = -1

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        **_kwargs,
    ) -> Iterator[Any]:
        import ray_tpu as rt

        self._epoch += 1
        epoch = self._epoch
        rt.get(self._coord.start_epoch.remote(self._index, epoch))

        def blocks() -> Iterator[B.Block]:
            while True:
                pair = rt.get(self._coord.next_block.remote(self._index, epoch))
                if pair is None:
                    return
                yield rt.get(pair[0])

        yield from rebatch(
            blocks(),
            batch_size=batch_size,
            batch_format=batch_format,
            drop_last=drop_last,
        )

    def iter_rows(self) -> Iterator[Dict]:
        for batch in self.iter_batches(batch_size=None):
            yield from B.iter_rows(batch)

    def iter_jax_batches(self, *, batch_size: int = 256, sharding=None,
                         dtype=None, drop_last: bool = True) -> Iterator[Any]:
        import jax
        import jax.numpy as jnp

        for batch in self.iter_batches(batch_size=batch_size, drop_last=drop_last):
            arrs = {
                k: (jnp.asarray(v, dtype=dtype) if dtype else jnp.asarray(v))
                for k, v in batch.items()
            }
            if sharding is not None:
                arrs = {k: jax.device_put(v, sharding) for k, v in arrs.items()}
            yield arrs


def make_streaming_split(dataset, n: int, *, equal: bool = False) -> List[DataIterator]:
    import ray_tpu as rt

    coord = rt.remote(_SplitCoordinator).options(
        num_cpus=0, max_concurrency=max(2, n + 1)
    ).remote(dataset, n, equal)
    return [DataIterator(coord, i, n) for i in range(n)]
