"""ray_tpu.data — lazy, streaming, distributed datasets.

Reference surface: `ray.data` (SURVEY §2.4 Ray Data): Dataset over
blocks with a lazy logical plan, map fusion, a streaming executor with
bounded in-flight work, and per-consumer streaming splits for Train.
"""

from ray_tpu.data import aggregate
from ray_tpu.data.aggregate import Count, Max, Mean, Min, Std, Sum
from ray_tpu.data.context import ActorPoolStrategy, DataContext
from ray_tpu.data.dataset import (
    Dataset,
    GroupedData,
    from_arrow,
    from_blocks,
    from_items,
    from_numpy,
    from_pandas,
    range,
    read_avro,
    read_binary_files,
    read_csv,
    read_images,
    read_json,
    read_numpy,
    read_parquet,
    read_sql,
    read_text,
    read_tfrecords,
)
from ray_tpu.data.iterator import DataIterator

__all__ = [
    "ActorPoolStrategy",
    "Count",
    "DataContext",
    "DataIterator",
    "Dataset",
    "GroupedData",
    "Max",
    "Mean",
    "Min",
    "Std",
    "Sum",
    "aggregate",
    "from_arrow",
    "from_blocks",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "read_avro",
    "read_binary_files",
    "read_csv",
    "read_images",
    "read_json",
    "read_numpy",
    "read_parquet",
    "read_sql",
    "read_text",
    "read_tfrecords",
]
