"""Aggregations for groupby/global aggregate.

Reference: `data/aggregate.py` (AggregateFn: Count/Sum/Min/Max/Mean/Std)
— each aggregation is (init, accumulate_block, merge, finalize) so maps
compute per-block partials and a reduce merges them.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

import numpy as np


@dataclass
class AggregateFn:
    init: Callable[[], Any]
    accumulate_block: Callable[[Any, np.ndarray], Any]
    merge: Callable[[Any, Any], Any]
    finalize: Callable[[Any], Any]
    name: str
    on: Optional[str] = None


def Count() -> AggregateFn:
    return AggregateFn(
        init=lambda: 0,
        accumulate_block=lambda a, col: a + len(col),
        merge=lambda a, b: a + b,
        finalize=lambda a: a,
        name="count()",
        on=None,
    )


def Sum(on: str) -> AggregateFn:
    return AggregateFn(
        init=lambda: 0.0,
        accumulate_block=lambda a, col: a + float(np.sum(col)),
        merge=lambda a, b: a + b,
        finalize=lambda a: a,
        name=f"sum({on})",
        on=on,
    )


def Min(on: str) -> AggregateFn:
    return AggregateFn(
        init=lambda: float("inf"),
        accumulate_block=lambda a, col: min(a, float(np.min(col))) if len(col) else a,
        merge=min,
        finalize=lambda a: a,
        name=f"min({on})",
        on=on,
    )


def Max(on: str) -> AggregateFn:
    return AggregateFn(
        init=lambda: float("-inf"),
        accumulate_block=lambda a, col: max(a, float(np.max(col))) if len(col) else a,
        merge=max,
        finalize=lambda a: a,
        name=f"max({on})",
        on=on,
    )


def Mean(on: str) -> AggregateFn:
    return AggregateFn(
        init=lambda: (0.0, 0),
        accumulate_block=lambda a, col: (a[0] + float(np.sum(col)), a[1] + len(col)),
        merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
        finalize=lambda a: a[0] / a[1] if a[1] else float("nan"),
        name=f"mean({on})",
        on=on,
    )


def Std(on: str, ddof: int = 1) -> AggregateFn:
    # Welford-style mergeable (sum, sum_sq, n)
    return AggregateFn(
        init=lambda: (0.0, 0.0, 0),
        accumulate_block=lambda a, col: (
            a[0] + float(np.sum(col)),
            a[1] + float(np.sum(np.square(col, dtype=np.float64))),
            a[2] + len(col),
        ),
        merge=lambda a, b: (a[0] + b[0], a[1] + b[1], a[2] + b[2]),
        finalize=lambda a: (
            float("nan")
            if a[2] <= ddof
            else float(np.sqrt(max(0.0, (a[1] - a[0] ** 2 / a[2]) / (a[2] - ddof))))
        ),
        name=f"std({on})",
        on=on,
    )
