"""Logical plan: lazy operator chain with map fusion.

Reference: Ray Data's logical plan + optimizer
(`data/_internal/logical/`, planner `_internal/planner/`).  The
capability kept: datasets are lazy; chained row/batch transforms fuse
into single tasks (the reference's MapFusion rule); all-to-all ops
(shuffle/sort/repartition/groupby) are explicit barrier stages.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.data import block as B


@dataclass
class ReadOp:
    """Source: a list of zero-arg callables, each returning a list of
    blocks (one read task per callable)."""

    read_tasks: List[Callable[[], List[B.Block]]]
    name: str = "Read"


@dataclass
class MapOp:
    """Per-block transform: Block -> List[Block].  map/map_batches/
    filter/flat_map/limit all lower to this shape."""

    fn: Callable[[B.Block], List[B.Block]]
    name: str = "Map"


@dataclass
class ActorMapOp:
    """Per-block transform executed on a pool of UDF-holding actors
    (reference: `actor_pool_map_operator.py`).  Never fused: the UDF
    instance carries state that must live in the actor."""

    cls: Any
    args: tuple
    kwargs: Dict[str, Any]
    batch_size: Optional[int]
    batch_format: str
    strategy: Any  # ActorPoolStrategy
    name: str = "ActorMap"


@dataclass
class ShuffleOp:
    """Distributed map-partition -> reduce-partition exchange
    (repartition, random_shuffle, sort, groupby) — the reference's
    push-based shuffle (`data/_internal/planner/exchange/`).  Replaces
    the old single-task AllToAll barrier: every map task partitions one
    input block into `num_partitions` pieces returned as separate
    lineage-backed objects, and every reduce task merges one
    partition's pieces, so a lost worker re-derives only its own
    blocks and an over-memory exchange spills through the object
    store instead of OOMing a gather task.

    `map_fn(block, block_index, num_partitions, aux) -> [P pieces]`;
    `reduce_fn(pieces, partition_index, aux) -> block`.  `aux` is the
    small plan-level payload (range boundaries, block offsets) built
    by `aux_fn(samples, metas, P)` after the optional `sample_fn` pass
    over input blocks.  Both fns MUST be deterministic: lineage
    reconstruction re-runs them to rebuild lost blocks mid-stream.
    """

    map_fn: Callable[[B.Block, int, int, Any], List[B.Block]]
    reduce_fn: Callable[[List[B.Block], int, Any], B.Block]
    num_partitions: Optional[int] = None
    sample_fn: Optional[Callable[[B.Block], Any]] = None
    aux_fn: Optional[Callable[[List[Any], List[Dict[str, Any]], int], Any]] = None
    name: str = "Shuffle"


@dataclass
class LimitOp:
    limit: int
    name: str = "Limit"


Op = Any  # ReadOp | MapOp | ShuffleOp | LimitOp


@dataclass
class LogicalPlan:
    ops: List[Op] = field(default_factory=list)

    def with_op(self, op: Op) -> "LogicalPlan":
        return LogicalPlan(self.ops + [op])

    def optimized(self) -> "LogicalPlan":
        """Fuse consecutive MapOps, then fold a leading Map into the
        Read tasks (reference MapFusion incl. read fusion) — one remote
        task reads AND transforms, halving task count and object-plane
        traffic for the common read->map_batches pipeline."""
        fused: List[Op] = []
        for op in self.ops:
            if (
                isinstance(op, MapOp)
                and fused
                and isinstance(fused[-1], MapOp)
            ):
                prev = fused.pop()
                fused.append(_fuse(prev, op))
            elif (
                isinstance(op, MapOp)
                and fused
                and isinstance(fused[-1], ReadOp)
            ):
                prev = fused.pop()
                fused.append(_fuse_read(prev, op))
            else:
                fused.append(op)
        return LogicalPlan(fused)

    def describe(self) -> str:
        return " -> ".join(op.name for op in self.ops)


def _fuse(a: MapOp, b: MapOp) -> MapOp:
    fa, fb = a.fn, b.fn

    def fused(block: B.Block) -> List[B.Block]:
        out: List[B.Block] = []
        for mid in fa(block):
            out.extend(fb(mid))
        return out

    return MapOp(fn=fused, name=f"{a.name}->{b.name}")


def _fuse_read(r: ReadOp, m: MapOp) -> ReadOp:
    fm = m.fn

    def make(task):
        def read_and_map() -> List[B.Block]:
            out: List[B.Block] = []
            for blk in task():
                out.extend(fm(blk))
            return out

        return read_and_map

    return ReadOp(
        read_tasks=[make(t) for t in r.read_tasks],
        name=f"{r.name}->{m.name}",
    )
