"""Datasources: read-task factories and write helpers.

Reference: `data/datasource/` + `_internal/datasource/` (parquet/csv/
json/numpy/range datasources).  A datasource here is simply a list of
zero-arg callables, each producing blocks — the executor turns each
into one remote read task (the reference's ReadTask contract).
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.data import block as B


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if not f.startswith(".")
            ))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched: {paths}")
    return out


def range_tasks(n: int, parallelism: int) -> List[Callable[[], List[B.Block]]]:
    parallelism = max(1, min(parallelism, n) if n else 1)
    bounds = np.linspace(0, n, parallelism + 1, dtype=np.int64)

    def make(lo: int, hi: int):
        return lambda: [{"id": np.arange(lo, hi, dtype=np.int64)}]

    return [make(int(bounds[i]), int(bounds[i + 1])) for i in range(parallelism)]


def items_tasks(items: List[Any], parallelism: int) -> List[Callable[[], List[B.Block]]]:
    n = len(items)
    parallelism = max(1, min(parallelism, n) if n else 1)
    bounds = np.linspace(0, n, parallelism + 1, dtype=np.int64)

    def make(chunk: List[Any]):
        return lambda: [B.from_items(chunk)]

    return [
        make(items[int(bounds[i]): int(bounds[i + 1])])
        for i in range(parallelism)
    ]


def blocks_tasks(blocks: List[B.Block]) -> List[Callable[[], List[B.Block]]]:
    def make(b: B.Block):
        return lambda: [b]

    return [make(b) for b in blocks]


def parquet_tasks(paths) -> List[Callable[[], List[B.Block]]]:
    files = _expand_paths(paths)

    def make(f: str):
        def read():
            import pyarrow.parquet as pq

            return [B.from_arrow(pq.read_table(f))]

        return read

    return [make(f) for f in files]


def csv_tasks(paths, **read_kwargs) -> List[Callable[[], List[B.Block]]]:
    files = _expand_paths(paths)

    def make(f: str):
        def read():
            import pyarrow.csv as pacsv

            return [B.from_arrow(pacsv.read_csv(f, **read_kwargs))]

        return read

    return [make(f) for f in files]


def json_tasks(paths) -> List[Callable[[], List[B.Block]]]:
    files = _expand_paths(paths)

    def make(f: str):
        def read():
            import json

            with open(f) as fh:
                first = fh.read(1)
                fh.seek(0)
                if first == "[":
                    rows = json.load(fh)
                else:  # JSONL
                    rows = [json.loads(line) for line in fh if line.strip()]
            return [B.from_rows(rows)]

        return read

    return [make(f) for f in files]


def text_tasks(paths) -> List[Callable[[], List[B.Block]]]:
    files = _expand_paths(paths)

    def make(f: str):
        def read():
            with open(f) as fh:
                lines = [ln.rstrip("\n") for ln in fh]
            return [{"text": np.asarray(lines, dtype=np.str_)}]

        return read

    return [make(f) for f in files]


def numpy_tasks(paths) -> List[Callable[[], List[B.Block]]]:
    """.npy (one `data` column) and .npz (one column per array) files
    (reference: `_internal/datasource/numpy_datasource.py`)."""
    files = _expand_paths(paths)

    def make(f: str):
        def read():
            if f.endswith(".npz"):
                with np.load(f) as z:
                    return [{k: z[k] for k in z.files}]
            return [{"data": np.load(f)}]

        return read

    return [make(f) for f in files]


def binary_tasks(paths, include_paths: bool = True) -> List[Callable[[], List[B.Block]]]:
    """Raw file bytes, one row per file (reference:
    `_internal/datasource/binary_datasource.py`).  Bytes land in an
    object-dtype column (ragged payloads)."""
    files = _expand_paths(paths)

    def make(f: str):
        def read():
            with open(f, "rb") as fh:
                data = fh.read()
            blk: B.Block = {
                "bytes": np.asarray([data], dtype=object),
            }
            if include_paths:
                blk["path"] = np.asarray([f])
            return [blk]

        return read

    return [make(f) for f in files]


_IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".tiff", ".webp")


def images_tasks(paths, size: Optional[tuple] = None,
                 mode: Optional[str] = None,
                 include_paths: bool = False) -> List[Callable[[], List[B.Block]]]:
    """Decoded images as HWC uint8 arrays — the TPU-training input
    format (reference: `_internal/datasource/image_datasource.py`,
    which also decodes eagerly into numpy).  `size=(h, w)` resizes so
    rows stack into one dense `image` tensor; without it, mixed
    dimensions fall back to an object column."""
    # directories filter to image extensions (a labels.csv next to the
    # images must not poison the read); explicitly named files pass
    # through untouched
    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if f.lower().endswith(_IMAGE_EXTS)
            ))
        else:
            files.extend(
                f for f in _expand_paths(p)
                if f.lower().endswith(_IMAGE_EXTS) or f == p
            )
    if not files:
        raise FileNotFoundError(f"no image files matched: {paths}")

    def make(f: str):
        def read():
            from PIL import Image

            with Image.open(f) as im:
                if mode:
                    im = im.convert(mode)
                if size is not None:
                    im = im.resize((size[1], size[0]))
                arr = np.asarray(im)
            if size is not None:
                col = arr[None]  # stackable: (1, h, w[, c])
            else:
                col = np.empty(1, dtype=object)
                col[0] = arr
            blk: B.Block = {"image": col}
            if include_paths:
                blk["path"] = np.asarray([f])
            return [blk]

        return read

    return [make(f) for f in files]


# ---- writers (run as map tasks) --------------------------------------
def write_numpy_block(path_dir: str, column: str = "data"):
    def write(blk: B.Block) -> List[B.Block]:
        import uuid

        arr = np.asarray(blk[column])
        if arr.dtype == object:
            # np.save would pickle these, and read_numpy (rightly)
            # loads with allow_pickle=False — fail loudly at write time
            raise ValueError(
                f"write_numpy: column {column!r} has object dtype "
                f"(ragged rows); convert to a dense dtype first"
            )
        os.makedirs(path_dir, exist_ok=True)
        f = os.path.join(path_dir, f"part-{uuid.uuid4().hex[:12]}.npy")
        np.save(f, arr, allow_pickle=False)
        return [{"path": np.asarray([f]),
                 "num_rows": np.asarray([B.num_rows(blk)])}]

    return write



def write_parquet_block(path_dir: str):
    def write(blk: B.Block) -> List[B.Block]:
        import uuid

        import pyarrow.parquet as pq

        os.makedirs(path_dir, exist_ok=True)
        f = os.path.join(path_dir, f"part-{uuid.uuid4().hex[:12]}.parquet")
        pq.write_table(B.to_arrow(blk), f)
        return [{"path": np.asarray([f]), "num_rows": np.asarray([B.num_rows(blk)])}]

    return write


def write_csv_block(path_dir: str):
    def write(blk: B.Block) -> List[B.Block]:
        import uuid

        os.makedirs(path_dir, exist_ok=True)
        f = os.path.join(path_dir, f"part-{uuid.uuid4().hex[:12]}.csv")
        B.to_pandas(blk).to_csv(f, index=False)
        return [{"path": np.asarray([f]), "num_rows": np.asarray([B.num_rows(blk)])}]

    return write


def write_json_block(path_dir: str):
    def write(blk: B.Block) -> List[B.Block]:
        import uuid

        os.makedirs(path_dir, exist_ok=True)
        f = os.path.join(path_dir, f"part-{uuid.uuid4().hex[:12]}.json")
        B.to_pandas(blk).to_json(f, orient="records", lines=True)
        return [{"path": np.asarray([f]), "num_rows": np.asarray([B.num_rows(blk)])}]

    return write


def tfrecord_tasks(paths, *, parse_example: bool = True,
                   verify: bool = True) -> List[Callable[[], List[B.Block]]]:
    """One read task per TFRecord file (reference:
    `_internal/datasource/tfrecords_datasource.py` — there TF-backed;
    here `data/tfrecord.py`'s native framing + tf.Example codec)."""
    files = _expand_paths(paths)

    def make(f: str):
        def read():
            from ray_tpu.data.tfrecord import read_tfrecords_rows

            return [B.from_rows(
                read_tfrecords_rows(f, parse_example=parse_example,
                                    verify=verify)
            )]

        return read

    return [make(f) for f in files]


def write_tfrecords_block(path_dir: str):
    """Write helper: each block becomes one .tfrecord file of
    tf.Examples (columns -> features)."""

    def write(blk: B.Block) -> List[B.Block]:
        import uuid

        from ray_tpu.data.tfrecord import encode_example, write_records

        os.makedirs(path_dir, exist_ok=True)
        f = os.path.join(path_dir, f"part-{uuid.uuid4().hex[:12]}.tfrecord")
        write_records(f, [
            encode_example(row) for row in B.iter_rows(blk)
        ])
        return [{"path": np.asarray([f]),
                 "num_rows": np.asarray([B.num_rows(blk)])}]

    return write


def avro_tasks(paths) -> List[Callable[[], List[B.Block]]]:
    """Avro object-container files (reference:
    `_internal/datasource/avro_datasource.py`); `data/avro.py` is a
    native reader for null/deflate codecs."""
    files = _expand_paths(paths)

    def make(f: str):
        def read():
            from ray_tpu.data.avro import read_avro_rows

            return [B.from_rows(read_avro_rows(f))]

        return read

    return [make(f) for f in files]


def sql_tasks(sql: str, connection_factory) -> List[Callable[[], List[B.Block]]]:
    """One read task running `sql` through a DB-API connection from
    `connection_factory` (reference: `_internal/datasource/
    sql_datasource.py` — same contract: the factory must be
    serializable, the connection is made ON the worker)."""

    def read():
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            cols = [d[0] for d in cur.description]
            rows = [dict(zip(cols, r)) for r in cur.fetchall()]
        finally:
            conn.close()
        return [B.from_rows(rows)]

    return [read]
