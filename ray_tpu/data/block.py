"""Block: the unit of data held in the object store.

Reference: `python/ray/data/block.py` — there a Block is an Arrow table
or pandas DataFrame behind a BlockAccessor.  Here the canonical
representation is a **dict of equal-length numpy arrays** (column-major):
zero-copy into the shm object plane, directly `device_put`-able for TPU
feeding — plus an **Arrow-table carrier** for IO-origin blocks whose
columns numpy would degrade (strings, binaries, nested lists stay
Arrow through slice/concat/rebatch instead of becoming object arrays;
VERDICT r2 weak #8).  Every helper below dispatches on the carrier;
compute ops that index columns numerically call :func:`ensure_numpy`
at entry.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

try:
    import pyarrow as pa
except ImportError:  # pragma: no cover - pyarrow is in the image
    pa = None

# dict-of-numpy or pyarrow.Table
Block = Any


def is_arrow_block(block) -> bool:
    return pa is not None and isinstance(block, pa.Table)


def _arrow_degrades_in_numpy(table) -> bool:
    """True when numpy conversion would produce object arrays (string /
    binary / nested types) — the case where keeping Arrow pays."""
    import pyarrow.types as pt

    return any(
        pt.is_string(f.type) or pt.is_large_string(f.type)
        or pt.is_binary(f.type) or pt.is_large_binary(f.type)
        or pt.is_list(f.type) or pt.is_large_list(f.type)
        or pt.is_struct(f.type) or pt.is_map(f.type)
        or pt.is_dictionary(f.type)
        for f in table.schema
    )


def _to_numpy(values: Sequence[Any]) -> np.ndarray:
    arr = np.asarray(values)
    if arr.dtype == object and values and isinstance(values[0], str):
        return np.asarray(values, dtype=np.str_)
    return arr


def from_rows(rows: List[Dict[str, Any]]) -> Block:
    if not rows:
        return {}
    cols = list(rows[0].keys())
    return {c: _to_numpy([r[c] for r in rows]) for c in cols}


def from_items(items: List[Any]) -> Block:
    if items and isinstance(items[0], dict):
        return from_rows(items)
    return {"item": _to_numpy(items)}


def num_rows(block: Block) -> int:
    if is_arrow_block(block):
        return block.num_rows
    for v in block.values():
        return len(v)
    return 0


def size_bytes(block: Block) -> int:
    if is_arrow_block(block):
        return int(block.nbytes)
    return int(sum(v.nbytes for v in block.values()))


def slice_block(block: Block, start: int, end: int) -> Block:
    if is_arrow_block(block):
        return block.slice(start, end - start)
    return {k: v[start:end] for k, v in block.items()}


def take_indices(block: Block, idx: np.ndarray) -> Block:
    if is_arrow_block(block):
        return block.take(pa.array(np.asarray(idx, dtype=np.int64)))
    return {k: v[idx] for k, v in block.items()}


def concat(blocks: Sequence[Block]) -> Block:
    blocks = [b for b in blocks if num_rows(b) > 0]
    if not blocks:
        return {}
    if all(is_arrow_block(b) for b in blocks):
        return pa.concat_tables(blocks, promote_options="default")
    if any(is_arrow_block(b) for b in blocks):
        # mixed carriers: normalize to numpy (rare — a map stage that
        # returned dicts downstream of an arrow read)
        blocks = [ensure_numpy(b) for b in blocks]
    cols = blocks[0].keys()
    return {c: np.concatenate([b[c] for b in blocks]) for c in cols}


def _item(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray) and v.shape == ():
        return v.item()
    return v


def iter_rows(block: Block) -> Iterable[Dict[str, Any]]:
    if is_arrow_block(block):
        for batch in block.to_batches():
            yield from batch.to_pylist()
        return
    n = num_rows(block)
    cols = list(block.keys())
    for i in range(n):
        yield {c: _item(block[c][i]) for c in cols}


def schema(block: Block) -> Optional[Dict[str, Any]]:
    if is_arrow_block(block):
        return {f.name: f.type for f in block.schema}
    if not block:
        return None
    return {k: v.dtype for k, v in block.items()}


def column_numpy(block: Block, name: str) -> np.ndarray:
    """One column as numpy WITHOUT converting sibling columns — sort
    and groupby key extraction must not pay the object-array conversion
    for the arrow carrier's string columns."""
    if is_arrow_block(block):
        col = block.column(name)
        try:
            return col.to_numpy(zero_copy_only=False)
        except Exception as e:
            # nested/extension arrow types have no numpy conversion:
            # fall back through python lists (slow path, keep visible)
            logger.debug("arrow->numpy fast path failed for column "
                         "%r (%s); using to_pylist", name, e)
            return np.asarray(col.to_pylist())
    return block[name]


def ensure_numpy(block: Block) -> Dict[str, np.ndarray]:
    """Dict-of-numpy view of any carrier — compute ops (sort, groupby,
    column math, device feeding) call this at entry."""
    if is_arrow_block(block):
        return _dict_from_arrow(block)
    return block


# ---- interop ---------------------------------------------------------
def to_pandas(block: Block):
    import pandas as pd

    if is_arrow_block(block):
        return block.to_pandas()
    return pd.DataFrame({
        k: (list(v) if v.ndim > 1 else v) for k, v in block.items()
    })


def from_pandas(df) -> Block:
    return {str(c): np.asarray(df[c].values) for c in df.columns}


def to_arrow(block: Block):
    if is_arrow_block(block):
        return block
    return pa.table({k: (v.tolist() if v.ndim > 1 else v) for k, v in block.items()})


def _dict_from_arrow(table) -> Dict[str, np.ndarray]:
    out = {}
    for name in table.column_names:
        col = table.column(name)
        try:
            out[name] = col.to_numpy(zero_copy_only=False)
        except Exception as e:
            logger.debug("arrow->numpy fast path failed for column "
                         "%r (%s); using to_pylist", name, e)
            out[name] = np.asarray(col.to_pylist())
    return out


def from_arrow(table, keep_arrow: Optional[bool] = None) -> Block:
    """IO boundary: purely-numeric tables become the numpy carrier (the
    TPU-feed fast path); tables with string/nested columns STAY Arrow
    so IO-bound pipelines never pay the object-array conversion.
    `keep_arrow` forces either way."""
    if keep_arrow is None:
        keep_arrow = _arrow_degrades_in_numpy(table)
    if keep_arrow:
        return table
    return _dict_from_arrow(table)


def format_batch(block: Block, batch_format: str):
    if batch_format in ("numpy", "default"):
        return ensure_numpy(block)
    if batch_format == "pandas":
        return to_pandas(block)
    if batch_format in ("pyarrow", "arrow"):
        return to_arrow(block)
    raise ValueError(f"unknown batch_format: {batch_format}")
