"""Block: the unit of data held in the object store.

Reference: `python/ray/data/block.py` — there a Block is an Arrow table
or pandas DataFrame behind a BlockAccessor.  Here the canonical
representation is a **dict of equal-length numpy arrays** (column-major):
zero-copy into the shm object plane, directly `device_put`-able for TPU
feeding, convertible to/from Arrow and pandas at the IO boundary.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

Block = Dict[str, np.ndarray]


def _to_numpy(values: Sequence[Any]) -> np.ndarray:
    arr = np.asarray(values)
    if arr.dtype == object and values and isinstance(values[0], str):
        return np.asarray(values, dtype=np.str_)
    return arr


def from_rows(rows: List[Dict[str, Any]]) -> Block:
    if not rows:
        return {}
    cols = list(rows[0].keys())
    return {c: _to_numpy([r[c] for r in rows]) for c in cols}


def from_items(items: List[Any]) -> Block:
    if items and isinstance(items[0], dict):
        return from_rows(items)
    return {"item": _to_numpy(items)}


def num_rows(block: Block) -> int:
    for v in block.values():
        return len(v)
    return 0


def size_bytes(block: Block) -> int:
    return int(sum(v.nbytes for v in block.values()))


def slice_block(block: Block, start: int, end: int) -> Block:
    return {k: v[start:end] for k, v in block.items()}

def take_indices(block: Block, idx: np.ndarray) -> Block:
    return {k: v[idx] for k, v in block.items()}


def concat(blocks: Sequence[Block]) -> Block:
    blocks = [b for b in blocks if num_rows(b) > 0]
    if not blocks:
        return {}
    cols = blocks[0].keys()
    return {c: np.concatenate([b[c] for b in blocks]) for c in cols}


def _item(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray) and v.shape == ():
        return v.item()
    return v


def iter_rows(block: Block) -> Iterable[Dict[str, Any]]:
    n = num_rows(block)
    cols = list(block.keys())
    for i in range(n):
        yield {c: _item(block[c][i]) for c in cols}


def schema(block: Block) -> Optional[Dict[str, np.dtype]]:
    if not block:
        return None
    return {k: v.dtype for k, v in block.items()}


# ---- interop ---------------------------------------------------------
def to_pandas(block: Block):
    import pandas as pd

    return pd.DataFrame({
        k: (list(v) if v.ndim > 1 else v) for k, v in block.items()
    })


def from_pandas(df) -> Block:
    return {str(c): np.asarray(df[c].values) for c in df.columns}


def to_arrow(block: Block):
    import pyarrow as pa

    return pa.table({k: (v.tolist() if v.ndim > 1 else v) for k, v in block.items()})


def from_arrow(table) -> Block:
    out = {}
    for name in table.column_names:
        col = table.column(name)
        try:
            out[name] = col.to_numpy(zero_copy_only=False)
        except Exception:
            out[name] = np.asarray(col.to_pylist())
    return out


def format_batch(block: Block, batch_format: str):
    if batch_format in ("numpy", "default"):
        return block
    if batch_format == "pandas":
        return to_pandas(block)
    if batch_format in ("pyarrow", "arrow"):
        return to_arrow(block)
    raise ValueError(f"unknown batch_format: {batch_format}")
