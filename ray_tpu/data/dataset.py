"""Dataset: lazy, streaming, distributed datasets.

Reference surface: `python/ray/data/dataset.py` (`Dataset`) — the same
transform/consume contract, executed by `ray_tpu.data.executor`'s
streaming pipeline over this framework's tasks + object plane.
TPU-native addition: `iter_jax_batches` device-puts batches with an
optional `NamedSharding` so a data-parallel mesh consumes host data
without an extra hop.
"""

from __future__ import annotations

import builtins
import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np

from ray_tpu.data import aggregate as agg_mod
from ray_tpu.data import block as B
from ray_tpu.data import datasource as ds_mod
from ray_tpu.data.executor import StreamingExecutor
from ray_tpu.data.plan import LimitOp, LogicalPlan, MapOp, ReadOp

DEFAULT_PARALLELISM = 8


class Dataset:
    def __init__(self, plan: LogicalPlan):
        self._plan = plan
        self._cached_pairs: Optional[List] = None  # materialized (ref, meta)
        # cached elastic split coordinator: (actor_handle, equal) — set
        # by streaming_split(elastic=True) so ingest reshards with the
        # training mesh instead of restarting the epoch
        self._split_coord = None

    # ------------------------------------------------------------------
    # construction helpers
    # ------------------------------------------------------------------
    def _with_op(self, op) -> "Dataset":
        return Dataset(self._plan.with_op(op))

    def _pairs(self) -> Iterator:
        if self._cached_pairs is not None:
            return iter(self._cached_pairs)
        return StreamingExecutor(self._plan).execute()

    def _iter_blocks(self) -> Iterator[B.Block]:
        import ray_tpu as rt

        for block_ref, _ in self._pairs():
            yield rt.get(block_ref)

    # ------------------------------------------------------------------
    # transforms (lazy)
    # ------------------------------------------------------------------
    def map(self, fn: Callable[[Dict], Dict]) -> "Dataset":
        def op(blk: B.Block) -> List[B.Block]:
            return [B.from_rows([fn(r) for r in B.iter_rows(blk)])]

        return self._with_op(MapOp(op, name="Map(map)"))

    def map_batches(
        self,
        fn: Callable,
        *,
        batch_size: Optional[int] = None,
        batch_format: str = "numpy",
        compute=None,
        fn_constructor_args: tuple = (),
        fn_constructor_kwargs: Optional[Dict] = None,
        **_kwargs,
    ) -> "Dataset":
        if compute is not None or isinstance(fn, type):
            from ray_tpu.data.context import ActorPoolStrategy
            from ray_tpu.data.plan import ActorMapOp

            if compute == "actors" or compute is None:
                compute = ActorPoolStrategy()
            if not isinstance(compute, ActorPoolStrategy):
                raise TypeError(
                    "compute= must be 'actors' or an ActorPoolStrategy"
                )
            if not isinstance(fn, type):
                raise TypeError(
                    "actor compute needs a class UDF (constructed once "
                    "per pool actor, called per batch)"
                )
            return self._with_op(ActorMapOp(
                cls=fn,
                args=tuple(fn_constructor_args),
                kwargs=dict(fn_constructor_kwargs or {}),
                batch_size=batch_size,
                batch_format=batch_format,
                strategy=compute,
                name=f"ActorMap({fn.__name__})",
            ))

        def op(blk: B.Block) -> List[B.Block]:
            out: List[B.Block] = []
            n = B.num_rows(blk)
            size = batch_size or n or 1
            for s in builtins.range(0, max(n, 1), size):
                piece = B.slice_block(blk, s, min(s + size, n))
                res = fn(B.format_batch(piece, batch_format))
                out.append(_coerce_batch(res))
            return out

        return self._with_op(MapOp(op, name="Map(map_batches)"))

    def flat_map(self, fn: Callable[[Dict], List[Dict]]) -> "Dataset":
        def op(blk: B.Block) -> List[B.Block]:
            rows: List[Dict] = []
            for r in B.iter_rows(blk):
                rows.extend(fn(r))
            return [B.from_rows(rows)]

        return self._with_op(MapOp(op, name="Map(flat_map)"))

    def filter(self, fn: Callable[[Dict], bool]) -> "Dataset":
        def op(blk: B.Block) -> List[B.Block]:
            mask = np.fromiter(
                (bool(fn(r)) for r in B.iter_rows(blk)),
                dtype=bool,
                count=B.num_rows(blk),
            )
            return [B.take_indices(blk, np.nonzero(mask)[0])]

        return self._with_op(MapOp(op, name="Map(filter)"))

    def add_column(self, name: str, fn: Callable[[B.Block], np.ndarray]) -> "Dataset":
        def op(blk: B.Block) -> List[B.Block]:
            blk = B.ensure_numpy(blk)
            out = dict(blk)
            out[name] = np.asarray(fn(blk))
            return [out]

        return self._with_op(MapOp(op, name="Map(add_column)"))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def op(blk: B.Block) -> List[B.Block]:
            if B.is_arrow_block(blk):
                return [blk.drop_columns([c for c in cols
                                          if c in blk.column_names])]
            return [{k: v for k, v in blk.items() if k not in cols}]

        return self._with_op(MapOp(op, name="Map(drop_columns)"))

    def select_columns(self, cols: List[str]) -> "Dataset":
        def op(blk: B.Block) -> List[B.Block]:
            if B.is_arrow_block(blk):
                return [blk.select(cols)]
            return [{k: blk[k] for k in cols}]

        return self._with_op(MapOp(op, name="Map(select_columns)"))

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        def op(blk: B.Block) -> List[B.Block]:
            if B.is_arrow_block(blk):
                return [blk.rename_columns(
                    [mapping.get(c, c) for c in blk.column_names])]
            return [{mapping.get(k, k): v for k, v in blk.items()}]

        return self._with_op(MapOp(op, name="Map(rename_columns)"))

    def limit(self, n: int) -> "Dataset":
        return self._with_op(LimitOp(n))

    def random_sample(self, fraction: float, *,
                      seed: Optional[int] = None) -> "Dataset":
        """Keep each row independently with probability `fraction`
        (reference: `Dataset.random_sample`)."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError("fraction must be in [0, 1]")
        # per-block streams: with a fixed seed, derive the block's
        # stream from (seed, first-row content) so the same data always
        # samples identically while distinct blocks stay decorrelated
        import zlib

        def op(blk: B.Block) -> List[B.Block]:
            n = B.num_rows(blk)
            if seed is None:
                rng = np.random.default_rng()
            else:
                first = next(B.iter_rows(blk), None)
                h = zlib.crc32(repr((n, first)).encode())
                rng = np.random.default_rng((seed, h))
            keep = np.nonzero(rng.random(n) < fraction)[0]
            return [B.take_indices(blk, keep)]

        return self._with_op(MapOp(op, name=f"RandomSample({fraction})"))

    def unique(self, column: str) -> List[Any]:
        """Distinct values of one column (reference: `Dataset.unique`)."""
        seen = []
        seen_set = set()
        for blk in self._iter_blocks():
            for v in np.asarray(B.column_numpy(blk, column)).tolist():
                k = v if not isinstance(v, list) else tuple(v)
                if k not in seen_set:
                    seen_set.add(k)
                    seen.append(v)
        return seen

    def train_test_split(self, test_size: float, *, shuffle: bool = True,
                         seed: Optional[int] = None
                         ) -> Tuple["Dataset", "Dataset"]:
        """Split into (train, test) datasets (reference:
        `Dataset.train_test_split`)."""
        if not 0.0 < test_size < 1.0:
            raise ValueError("test_size must be in (0, 1)")
        import ray_tpu as rt

        ds = (self.random_shuffle(seed=seed) if shuffle else self
              ).materialize()
        pairs = ds._cached_pairs
        n = builtins.sum(int(m["num_rows"]) for _, m in pairs)
        n_test = max(1, int(n * test_size))
        n_train = n - n_test
        # split at the row boundary WITHOUT pulling blocks to the
        # driver: whole blocks keep their refs; only the boundary block
        # is sliced, remotely
        train_pairs, test_pairs = [], []
        cum = 0
        for ref, meta in pairs:
            rows = int(meta["num_rows"])
            if cum + rows <= n_train:
                train_pairs.append((ref, meta))
            elif cum >= n_train:
                test_pairs.append((ref, meta))
            else:
                k = n_train - cum
                left_ref, right_ref = rt.remote(_split_block).options(
                    num_returns=2, num_cpus=1
                ).remote(ref, k)
                train_pairs.append(
                    (left_ref, {"num_rows": k,
                                "size_bytes": meta.get("size_bytes", 0)})
                )
                test_pairs.append(
                    (right_ref, {"num_rows": rows - k,
                                 "size_bytes": meta.get("size_bytes", 0)})
                )
            cum += rows
        train = Dataset(LogicalPlan([ReadOp([], name="TrainSplit")]))
        test = Dataset(LogicalPlan([ReadOp([], name="TestSplit")]))
        train._cached_pairs = train_pairs
        test._cached_pairs = test_pairs
        return train, test

    # ---- all-to-all (distributed shuffle, `data/shuffle.py`) ---------
    def repartition(self, num_blocks: int) -> "Dataset":
        from ray_tpu.data.shuffle import repartition_op

        return self._with_op(repartition_op(num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        from ray_tpu.data.shuffle import random_shuffle_op

        return self._with_op(random_shuffle_op(seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        from ray_tpu.data.context import DataContext
        from ray_tpu.data.shuffle import sort_op

        return self._with_op(sort_op(
            key, descending,
            sample_rows=DataContext.get_current().shuffle_sample_rows,
        ))

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def union(self, *others: "Dataset") -> "Dataset":
        # pure metadata concat: block refs stay where they are
        pairs = list(self.materialize()._cached_pairs)
        for o in others:
            pairs.extend(o.materialize()._cached_pairs)
        out = Dataset(LogicalPlan([ReadOp([], name="Union")]))
        out._cached_pairs = pairs
        return out

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-concatenate two datasets row-aligned.  Runs as one
        remote task over block REFS — payloads never touch the driver."""
        import ray_tpu as rt

        left = [p[0] for p in self.materialize()._cached_pairs]
        right = [p[0] for p in other.materialize()._cached_pairs]
        zip_remote = rt.remote(_zip_task).options(num_cpus=1)
        pairs = rt.get(zip_remote.remote(len(left), *left, *right))
        out = Dataset(LogicalPlan([ReadOp([], name="Zip")]))
        out._cached_pairs = pairs
        return out

    # ------------------------------------------------------------------
    # consumption
    # ------------------------------------------------------------------
    def iter_rows(self) -> Iterator[Dict]:
        for blk in self._iter_blocks():
            yield from B.iter_rows(blk)

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
    ) -> Iterator[Any]:
        from ray_tpu.data.iterator import rebatch, shuffle_buffer

        blocks = self._iter_blocks()
        if local_shuffle_buffer_size:
            blocks = shuffle_buffer(
                blocks, local_shuffle_buffer_size, local_shuffle_seed
            )
        yield from rebatch(
            blocks,
            batch_size=batch_size,
            batch_format=batch_format,
            drop_last=drop_last,
        )

    def iter_jax_batches(
        self,
        *,
        batch_size: int = 256,
        sharding=None,
        dtype=None,
        drop_last: bool = True,
    ) -> Iterator[Any]:
        """Batches as device-resident jax arrays (TPU feed path)."""
        import jax
        import jax.numpy as jnp

        for batch in self.iter_batches(batch_size=batch_size, drop_last=drop_last):
            arrs = {
                k: (jnp.asarray(v, dtype=dtype) if dtype else jnp.asarray(v))
                for k, v in batch.items()
            }
            if sharding is not None:
                arrs = {k: jax.device_put(v, sharding) for k, v in arrs.items()}
            yield arrs

    def iter_torch_batches(
        self,
        *,
        batch_size: int = 256,
        dtypes=None,
        drop_last: bool = False,
    ) -> Iterator[Any]:
        """Batches as torch tensors (reference:
        `data/iterator.py` iter_torch_batches); dtypes maps column ->
        torch dtype."""
        import torch

        for batch in self.iter_batches(batch_size=batch_size, drop_last=drop_last):
            out = {}
            for k, v in batch.items():
                t = torch.as_tensor(np.ascontiguousarray(v))
                if dtypes and k in dtypes:
                    t = t.to(dtypes[k])
                out[k] = t
            yield out

    def take(self, n: int = 20) -> List[Dict]:
        return list(itertools.islice(self.iter_rows(), n))

    def take_all(self) -> List[Dict]:
        return list(self.iter_rows())

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def count(self) -> int:
        from ray_tpu.data.executor import resolve_metas

        # one batched metadata get, not one blocking get per block
        return builtins.sum(
            m["num_rows"]
            for m in resolve_metas([meta for _, meta in self._pairs()])
        )

    def schema(self) -> Optional[Dict[str, np.dtype]]:
        for blk in self._iter_blocks():
            s = B.schema(blk)
            if s:
                return s
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.keys()) if s else []

    def num_blocks(self) -> int:
        return sum(1 for _ in self._pairs())

    def size_bytes(self) -> int:
        from ray_tpu.data.executor import resolve_metas

        return builtins.sum(
            m.get("size_bytes", 0)
            for m in resolve_metas([meta for _, meta in self._pairs()])
        )

    def to_pandas(self):
        return B.to_pandas(B.concat(list(self._iter_blocks())))

    def materialize(self) -> "Dataset":
        """Execute now; the result holds block refs (reference:
        `Dataset.materialize` -> MaterializedDataset)."""
        from ray_tpu.data.executor import resolve_pairs

        out = Dataset(LogicalPlan([ReadOp([], name="Materialized")]))
        out._cached_pairs = resolve_pairs(list(self._pairs()))
        return out

    def stats(self) -> str:
        ex = StreamingExecutor(self._plan)
        return f"plan: {ex.plan.describe()}"

    # ---- splits -------------------------------------------------------
    def split(self, n: int) -> List["Dataset"]:
        import ray_tpu as rt

        pairs = self.materialize()._cached_pairs
        out = []
        for i in builtins.range(n):
            d = Dataset(LogicalPlan([ReadOp([], name="Split")]))
            d._cached_pairs = pairs[i::n]
            out.append(d)
        return out

    def streaming_split(self, n: int, *, equal: bool = False,
                        elastic: bool = False) -> List["DataIterator"]:
        """N concurrent consumers over ONE shared streaming execution.

        With ``elastic=True`` the split coordinator is cached on this
        dataset and survives consumer re-formation: a later
        ``streaming_split(m, elastic=True)`` RESHARDS the in-progress
        epoch to ``m`` consumers instead of restarting it — delivered-
        but-unacknowledged blocks are requeued, acknowledged blocks are
        never redelivered, so every block is consumed exactly once
        across a mesh shrink/re-grow (the elastic-training ingest
        path, `train/backend_executor.py`)."""
        from ray_tpu.data.iterator import make_streaming_split

        return make_streaming_split(self, n, equal=equal, elastic=elastic)

    # ---- writes -------------------------------------------------------
    def _write(self, write_factory, path: str) -> int:
        results = self._with_op(
            MapOp(write_factory(path), name="Map(write)")
        ).take_all()
        return builtins.sum(int(r["num_rows"]) for r in results)

    def write_parquet(self, path: str) -> int:
        return self._write(ds_mod.write_parquet_block, path)

    def write_csv(self, path: str) -> int:
        return self._write(ds_mod.write_csv_block, path)

    def write_json(self, path: str) -> int:
        return self._write(ds_mod.write_json_block, path)

    def write_numpy(self, path: str, column: str = "data") -> int:
        return self._write(
            lambda p: ds_mod.write_numpy_block(p, column), path
        )

    def write_tfrecords(self, path: str) -> int:
        return self._write(ds_mod.write_tfrecords_block, path)

    # ---- global aggregates -------------------------------------------
    def aggregate(self, *aggs: agg_mod.AggregateFn) -> Dict[str, Any]:
        states = [a.init() for a in aggs]
        for blk in self._iter_blocks():
            n = B.num_rows(blk)
            for i, a in enumerate(aggs):
                col = blk[a.on] if a.on else np.empty(n)
                states[i] = a.accumulate_block(states[i], col)
        return {a.name: a.finalize(s) for a, s in zip(aggs, states)}

    def sum(self, on: str):
        return self.aggregate(agg_mod.Sum(on))[f"sum({on})"]

    def min(self, on: str):
        return self.aggregate(agg_mod.Min(on))[f"min({on})"]

    def max(self, on: str):
        return self.aggregate(agg_mod.Max(on))[f"max({on})"]

    def mean(self, on: str):
        return self.aggregate(agg_mod.Mean(on))[f"mean({on})"]

    def std(self, on: str, ddof: int = 1):
        return self.aggregate(agg_mod.Std(on, ddof))[f"std({on})"]

    def __repr__(self):
        return f"Dataset(plan={self._plan.describe()})"


class GroupedData:
    """Reference: `data/grouped_data.py` — partial per-block aggregation
    merged in an all-to-all reduce."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def aggregate(self, *aggs: agg_mod.AggregateFn) -> Dataset:
        from ray_tpu.data.context import DataContext
        from ray_tpu.data.shuffle import groupby_aggregate_op

        return self._ds._with_op(groupby_aggregate_op(
            self._key, tuple(aggs),
            sample_rows=DataContext.get_current().shuffle_sample_rows,
        ))

    def count(self) -> Dataset:
        return self.aggregate(agg_mod.Count())

    def sum(self, on: str) -> Dataset:
        return self.aggregate(agg_mod.Sum(on))

    def mean(self, on: str) -> Dataset:
        return self.aggregate(agg_mod.Mean(on))

    def min(self, on: str) -> Dataset:
        return self.aggregate(agg_mod.Min(on))

    def max(self, on: str) -> Dataset:
        return self.aggregate(agg_mod.Max(on))

    def std(self, on: str, ddof: int = 1) -> Dataset:
        return self.aggregate(agg_mod.Std(on, ddof))

    def map_groups(self, fn: Callable[[B.Block], Any]) -> Dataset:
        from ray_tpu.data.context import DataContext
        from ray_tpu.data.shuffle import map_groups_op

        return self._ds._with_op(map_groups_op(
            self._key, fn,
            sample_rows=DataContext.get_current().shuffle_sample_rows,
        ))


def _zip_task(n_left: int, *blocks):
    """Remote: zip left/right block lists; returns (ref, meta) pairs."""
    import ray_tpu as rt

    left = B.ensure_numpy(B.concat(list(blocks[:n_left])))
    right = B.ensure_numpy(B.concat(list(blocks[n_left:])))
    if B.num_rows(left) != B.num_rows(right):
        raise ValueError("zip requires equal row counts")
    merged = dict(left)
    for k, v in right.items():
        merged[k if k not in merged else f"{k}_1"] = v
    ref = rt.put(merged)
    return [(ref, {"num_rows": B.num_rows(merged), "size_bytes": B.size_bytes(merged)})]


def _split_block(blk: B.Block, k: int):
    """Remote boundary-block split for train_test_split."""
    return B.slice_block(blk, 0, k), B.slice_block(blk, k, B.num_rows(blk))


def _coerce_batch(res) -> B.Block:
    if isinstance(res, dict):
        return {k: np.asarray(v) for k, v in res.items()}
    try:
        import pandas as pd

        if isinstance(res, pd.DataFrame):
            return B.from_pandas(res)
    except ImportError:
        pass
    try:
        import pyarrow as pa

        if isinstance(res, pa.Table):
            return B.from_arrow(res)
    except ImportError:
        pass
    raise TypeError(
        f"map_batches fn must return dict/DataFrame/Table, got {type(res)}"
    )


# ---------------------------------------------------------------------
# read API (reference: `ray.data.read_*` / `from_*` in data/read_api.py)
# ---------------------------------------------------------------------
def _read_ds(tasks, name) -> Dataset:
    from ray_tpu.util.usage_stats import record_library_usage

    record_library_usage("data")
    return Dataset(LogicalPlan([ReadOp(tasks, name=name)]))


def range(n: int, *, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:  # noqa: A001
    return _read_ds(ds_mod.range_tasks(n, parallelism), f"Read(range[{n}])")


def from_items(items: List[Any], *, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    return _read_ds(ds_mod.items_tasks(list(items), parallelism), "Read(items)")


def from_blocks(blocks: List[B.Block]) -> Dataset:
    return _read_ds(ds_mod.blocks_tasks(blocks), "Read(blocks)")


def from_numpy(arr: np.ndarray, column: str = "data",
               *, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    chunks = np.array_split(arr, max(1, min(parallelism, len(arr))))
    return from_blocks([{column: c} for c in chunks if len(c)])


def from_pandas(df) -> Dataset:
    return from_blocks([B.from_pandas(df)])


def from_arrow(table) -> Dataset:
    return from_blocks([B.from_arrow(table)])


def read_parquet(paths) -> Dataset:
    return _read_ds(ds_mod.parquet_tasks(paths), "Read(parquet)")


def read_csv(paths, **kwargs) -> Dataset:
    return _read_ds(ds_mod.csv_tasks(paths, **kwargs), "Read(csv)")


def read_json(paths) -> Dataset:
    return _read_ds(ds_mod.json_tasks(paths), "Read(json)")


def read_text(paths) -> Dataset:
    return _read_ds(ds_mod.text_tasks(paths), "Read(text)")


def read_numpy(paths) -> Dataset:
    return _read_ds(ds_mod.numpy_tasks(paths), "Read(numpy)")


def read_binary_files(paths, include_paths: bool = True) -> Dataset:
    return _read_ds(
        ds_mod.binary_tasks(paths, include_paths=include_paths),
        "Read(binary)",
    )


def read_images(paths, size=None, mode=None,
                include_paths: bool = False) -> Dataset:
    return _read_ds(
        ds_mod.images_tasks(paths, size=size, mode=mode,
                            include_paths=include_paths),
        "Read(images)",
    )


def read_tfrecords(paths, *, parse_example: bool = True,
                   verify: bool = True) -> Dataset:
    return _read_ds(
        ds_mod.tfrecord_tasks(paths, parse_example=parse_example,
                              verify=verify),
        "Read(tfrecords)",
    )


def read_avro(paths) -> Dataset:
    return _read_ds(ds_mod.avro_tasks(paths), "Read(avro)")


def read_sql(sql: str, connection_factory) -> Dataset:
    return _read_ds(ds_mod.sql_tasks(sql, connection_factory), "Read(sql)")
