"""Distributed push-based shuffle for the streaming executor.

Reference: `data/_internal/planner/exchange/` — the map-partition ->
reduce-partition exchange behind repartition/random_shuffle/sort/
groupby.  The old executor ran every all-to-all as ONE remote task
that gathered the whole dataset (an OOM barrier and a single point of
failure); here each input block is partitioned by its own map task
into P pieces returned as separate objects, and each of the P reduce
tasks merges one partition — so:

- **failure isolation**: map/reduce tasks carry
  `DataContext.data_task_max_retries`, so a SIGKILLed worker retries
  through the core worker-died path; a lost piece re-derives via
  lineage reconstruction, and a lost reducer re-pulls only its own
  partition;
- **memory**: no task ever holds more than one block (map) or one
  partition (reduce); the full exchange lives in the object store,
  which spills past the high watermark — a shuffle of a dataset
  larger than the store completes (`tests/test_spilling.py` plane);
- **backpressure**: map admission is count- AND byte-bounded; when an
  admission point can make no progress within
  `backpressure_timeout_s` it raises a typed
  :class:`~ray_tpu.exceptions.BackPressureError` instead of queueing
  unboundedly or hanging.

Every map/reduce closure built here is DETERMINISTIC (seeds are baked
at plan time) — lineage reconstruction re-runs them to rebuild lost
blocks mid-stream, and a nondeterministic re-run would silently
drop/duplicate rows across the recovery boundary.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ray_tpu.data import block as B
from ray_tpu.exceptions import BackPressureError
from ray_tpu.metrics import metric_defs as _mdefs
from ray_tpu.util import tracing as _tracing

logger = logging.getLogger(__name__)


# ----------------------------------------------------------------------
# remote task bodies
# ----------------------------------------------------------------------
def _sample_task(sample_fn, blk: B.Block):
    return sample_fn(blk)


def _shuffle_map_task(map_fn, block_index: int, num_partitions: int, aux,
                      blk: B.Block):
    """One input block -> P partition pieces + a small accounting meta.
    Returned as P+1 separate objects so each piece is an independently
    lineage-reconstructable unit."""
    pieces = map_fn(blk, block_index, num_partitions, aux)
    assert len(pieces) == num_partitions, (
        f"map_fn returned {len(pieces)} pieces for {num_partitions} "
        "partitions"
    )
    meta = {
        "rows": [B.num_rows(p) for p in pieces],
        "bytes": [B.size_bytes(p) for p in pieces],
    }
    return (*pieces, meta)


def _shuffle_reduce_task(reduce_fn, partition_index: int, aux, *pieces):
    out = reduce_fn(list(pieces), partition_index, aux)
    return out, {"num_rows": B.num_rows(out), "size_bytes": B.size_bytes(out)}


# ----------------------------------------------------------------------
# the exchange driver (called by StreamingExecutor._shuffle_stream)
# ----------------------------------------------------------------------
def run_shuffle(executor, stream: Iterator[Tuple[Any, Any]], op
                ) -> Iterator[Tuple[Any, Any]]:
    """Drive one ShuffleOp: drain the upstream stream (refs only — the
    upstream stages keep their own windows; payloads never touch the
    driver), optionally sample, then map-partition and reduce with
    bounded in-flight work.  Yields (block_ref, meta_ref) pairs in
    partition order as reducers are admitted, so a slow downstream
    consumer paces reduce submission."""
    import ray_tpu as rt

    ctx_window = executor.window
    max_bytes = executor.max_stage_bytes
    ctx = executor.ctx
    retries = ctx.data_task_max_retries
    bp_timeout = ctx.backpressure_timeout_s

    # 1. collect input refs (drives the upstream pipeline; a metadata
    # barrier over refs, never a data barrier on the driver)
    pairs = list(stream)
    if not pairs:
        return
    metas = executor.resolve_metas([m for _, m in pairs])
    n_in = len(pairs)
    P = op.num_partitions or ctx.shuffle_partitions
    if not P:
        # memory-adaptive partition count: size partitions so one
        # in-flight reducer (pinned pieces + merged output, the 2x
        # below) fits in HALF the stage budget — leaving the other
        # half for the downstream consumer's pinned batches.  This is
        # what lets a shuffle of a dataset far larger than the object
        # store stream through it (reference: target-block-size
        # splitting in the exchange planner).
        total_bytes = sum(int(m.get("size_bytes", 0)) for m in metas)
        P = max(n_in, -(-4 * total_bytes // max(1, max_bytes)))
        P = min(P, 4096, max(1, sum(
            int(m.get("num_rows", 0)) for m in metas
        )))

    # 2. optional sample pass (sort/groupby range boundaries): small
    # per-block samples gathered on the driver — the only values a
    # shuffle ever pulls locally
    samples: Optional[List[Any]] = None
    if op.sample_fn is not None:
        sample_remote = rt.remote(_sample_task).options(
            num_cpus=executor.task_num_cpus, max_retries=retries
        )
        sample_refs = []
        for ref, _ in pairs:
            sample_refs.append(sample_remote.remote(op.sample_fn, ref))
            executor.stats["tasks"] += 1
        samples = rt.get(sample_refs)
    aux = op.aux_fn(samples, metas, P) if op.aux_fn is not None else None

    # umbrella span for the whole exchange: every map/reduce/sample
    # task submitted below nests under it, so the map→reduce lineage of
    # one shuffle shares ONE trace id in the collected timeline.
    # Explicit (not a `with` block): this function is a generator, and
    # a context manager across yields would leak the ambient trace
    # context into the consumer (see util/tracing.py).
    ex_span = _tracing.start_span(op.name, kind="SHUFFLE")
    ex_ctx = _tracing.ctx_of(ex_span)

    # 3. map phase: count- and byte-bounded admission.  The byte cost
    # of a running map task is ~2x its input (pinned input + created
    # pieces); pinned bytes can neither spill nor evict, so the sum of
    # in-flight costs must stay under the store-aware stage budget or
    # an over-memory shuffle wedges every create.
    # completion ref -> (est task bytes, admit instant, phase)
    outstanding: Dict[Any, tuple] = {}
    inflight_bytes = 0

    def _drain_one(where: str) -> None:
        """Reap at least one completed task or raise the typed
        backpressure error (bounded queue, never a hang)."""
        nonlocal inflight_bytes
        done, _ = rt.wait(
            list(outstanding), num_returns=1, timeout=bp_timeout,
        )
        if not done:
            phase = where.split()[0]
            _mdefs.inc("rt_shuffle_backpressure_total",
                       tags={"phase": phase})
            _tracing.record_instant(
                f"backpressure:{op.name}", ex_ctx, kind="BACKPRESSURE",
                where=where,
            )
            raise BackPressureError(
                f"shuffle {where} made no progress for "
                f"{bp_timeout:.0f}s at {len(outstanding)} in-flight "
                f"tasks / {inflight_bytes} bytes "
                f"(stage budget {max_bytes} bytes)",
                retry_after_s=bp_timeout,
            )
        now = time.monotonic()
        for m in done:
            cost, t_admit, phase = outstanding.pop(m)
            inflight_bytes -= cost
            _mdefs.observe("rt_shuffle_partition_seconds", now - t_admit,
                           tags={"phase": phase})

    def _admit(cost: int, where: str) -> None:
        while len(outstanding) >= ctx_window or (
            outstanding and inflight_bytes + cost > max_bytes
        ):
            _drain_one(where)

    map_remote = rt.remote(_shuffle_map_task).options(
        num_cpus=executor.task_num_cpus,
        num_returns=P + 1,
        max_retries=retries,
    )
    map_outs: List[Optional[List[Any]]] = [None] * n_in
    map_meta_refs: List[Any] = []
    rows_in = 0
    try:
        for i, (ref, _) in enumerate(pairs):
            cost = 2 * int(metas[i].get("size_bytes", 0))
            rows_in += int(metas[i].get("num_rows", 0))
            _admit(cost, "map admission")
            with _tracing.use_context(ex_ctx):
                rets = map_remote.remote(op.map_fn, i, P, aux, ref)
            executor.stats["tasks"] += 1
            map_outs[i] = list(rets[:P])
            map_meta_refs.append(rets[P])
            outstanding[rets[P]] = (cost, time.monotonic(), "map")
            inflight_bytes += cost
        while outstanding:
            _drain_one("map drain")
        _mdefs.inc("rt_shuffle_rows_total", float(rows_in))

        # per-partition sizes from the map metas (one batched get):
        # exact row accounting + byte-accounted reduce admission
        map_metas = rt.get(map_meta_refs)
        part_rows = [0] * P
        part_bytes = [0] * P
        for m in map_metas:
            for r in range(P):
                part_rows[r] += int(m["rows"][r])
                part_bytes[r] += int(m["bytes"][r])
        executor.stats.setdefault("shuffle", []).append(
            {"op": op.name, "inputs": n_in, "partitions": P,
             "rows_in": rows_in, "rows_mapped": sum(part_rows)}
        )

        # 4. reduce phase: byte-accounted bounded in-flight partitions,
        # streamed downstream in partition order as they are admitted
        red_remote = rt.remote(_shuffle_reduce_task).options(
            num_cpus=executor.task_num_cpus,
            num_returns=2,
            max_retries=retries,
        )
        for r in range(P):
            cost = 2 * part_bytes[r]  # pinned pieces + merged output
            _admit(cost, f"reduce admission (partition {r})")
            pieces = [map_outs[i][r] for i in range(n_in)]
            with _tracing.use_context(ex_ctx):
                block_ref, meta_ref = red_remote.remote(
                    op.reduce_fn, r, aux, *pieces
                )
            executor.stats["tasks"] += 1
            outstanding[meta_ref] = (cost, time.monotonic(), "reduce")
            inflight_bytes += cost
            for i in range(n_in):  # release pieces as they are consumed
                map_outs[i][r] = None
            yield block_ref, meta_ref
    finally:
        # runs at exhaustion AND at abandonment (generator close), so
        # the umbrella span always lands in the trace with its real
        # duration
        _tracing.finish_span(ex_span)


# ----------------------------------------------------------------------
# op factories (used by Dataset)
# ----------------------------------------------------------------------
def _bake_seed(seed: Optional[int]) -> int:
    """A concrete seed even for seed=None: map/reduce closures must be
    deterministic so lineage reconstruction re-derives identical
    blocks — an unseeded rng re-run after a worker loss would
    silently drop/duplicate rows across the recovery boundary."""
    if seed is not None:
        return int(seed)
    return int(np.random.SeedSequence().entropy) % (2**31)


def repartition_op(num_blocks: int):
    """Exact contiguous repartition: aux carries global row offsets
    (from input metadata), each map task slices its rows into the
    global target ranges, reducers concat pieces in block order — so
    row order is preserved end to end."""
    from ray_tpu.data.plan import ShuffleOp

    if num_blocks < 1:
        raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")

    def aux_fn(_samples, metas, P):
        rows = [int(m.get("num_rows", 0)) for m in metas]
        offsets = np.concatenate([[0], np.cumsum(rows)])
        bounds = np.linspace(0, int(offsets[-1]), P + 1, dtype=np.int64)
        return {"offsets": offsets.tolist(), "bounds": bounds.tolist()}

    def map_fn(blk, i, P, aux):
        start = aux["offsets"][i]
        n = B.num_rows(blk)
        bounds = np.asarray(aux["bounds"], dtype=np.int64)
        cut = np.clip(bounds - start, 0, n)
        return [B.slice_block(blk, int(cut[r]), int(cut[r + 1]))
                for r in range(P)]

    def reduce_fn(pieces, _r, _aux):
        return B.concat(pieces)

    return ShuffleOp(
        map_fn=map_fn, reduce_fn=reduce_fn, num_partitions=num_blocks,
        aux_fn=aux_fn, name=f"Shuffle(repartition[{num_blocks}])",
    )


def random_shuffle_op(seed: Optional[int]):
    """Seeded two-level shuffle: map scatters each row to a uniform
    partition, reduce permutes within its partition.  Streams are
    derived from (seed, role, index) so re-derivation after a loss is
    bit-identical."""
    from ray_tpu.data.plan import ShuffleOp

    baked = _bake_seed(seed)

    def map_fn(blk, i, P, _aux):
        rng = np.random.default_rng((baked, 0x5EED, i))
        assign = rng.integers(0, P, B.num_rows(blk))
        return [B.take_indices(blk, np.nonzero(assign == r)[0])
                for r in range(P)]

    def reduce_fn(pieces, r, _aux):
        full = B.concat(pieces)
        rng = np.random.default_rng((baked, 0xD00D, r))
        return B.take_indices(full, rng.permutation(B.num_rows(full)))

    return ShuffleOp(
        map_fn=map_fn, reduce_fn=reduce_fn,
        name="Shuffle(random_shuffle)",
    )


def _key_sample_fn(key: str, sample_rows: int):
    def sample(blk):
        keys = np.asarray(B.column_numpy(blk, key))
        n = len(keys)
        if n <= sample_rows:
            return keys
        idx = np.linspace(0, n - 1, sample_rows).astype(np.int64)
        return keys[idx]

    return sample


def _range_boundaries(samples: List[Any], P: int) -> np.ndarray:
    """P-1 boundary keys from the per-block samples: equal-count
    quantiles of the pooled (sorted) sample."""
    pool = np.sort(np.concatenate([np.asarray(s) for s in samples]))
    if P <= 1 or len(pool) == 0:
        return pool[:0]
    idx = [min(len(pool) - 1, (len(pool) * r) // P) for r in range(1, P)]
    return pool[idx]


def _range_partition(blk, P: int, boundaries: np.ndarray, key: str,
                     descending: bool = False) -> List[B.Block]:
    keys = np.asarray(B.column_numpy(blk, key))
    part = np.searchsorted(boundaries, keys, side="right")
    if descending:
        part = (P - 1) - part
    return [B.take_indices(blk, np.nonzero(part == r)[0]) for r in range(P)]


def sort_op(key: str, descending: bool = False, *, sample_rows: int = 64):
    """Range-partitioned sort: sample -> boundaries -> partition ->
    per-partition stable sort.  Partition order IS global order."""
    from ray_tpu.data.plan import ShuffleOp

    def aux_fn(samples, _metas, P):
        return _range_boundaries(samples, P)

    def map_fn(blk, _i, P, aux):
        return _range_partition(blk, P, aux, key, descending=descending)

    def reduce_fn(pieces, _r, _aux):
        full = B.concat(pieces)
        if not B.num_rows(full):
            return full
        order = np.argsort(np.asarray(B.column_numpy(full, key)),
                           kind="stable")
        if descending:
            order = order[::-1]
        return B.take_indices(full, order)

    return ShuffleOp(
        map_fn=map_fn, reduce_fn=reduce_fn,
        sample_fn=_key_sample_fn(key, sample_rows), aux_fn=aux_fn,
        name=f"Shuffle(sort[{key}{' desc' if descending else ''}])",
    )


def groupby_aggregate_op(key: str, aggs: tuple, *, sample_rows: int = 64):
    """Range-partitioned groupby: equal keys land in exactly one
    partition (searchsorted is deterministic per key value), each
    reducer aggregates its complete groups and emits rows in key
    order — globally ordered output like the sort."""
    from ray_tpu.data.plan import ShuffleOp

    def aux_fn(samples, _metas, P):
        return _range_boundaries(samples, P)

    def map_fn(blk, _i, P, aux):
        return _range_partition(blk, P, aux, key)

    def reduce_fn(pieces, _r, _aux):
        groups: Dict[Any, List[Any]] = {}
        for blk in pieces:
            if not B.num_rows(blk):
                continue
            keys = np.asarray(B.column_numpy(blk, key))
            for g in np.unique(keys):
                idx = np.nonzero(keys == g)[0]
                sub = B.ensure_numpy(B.take_indices(blk, idx))
                gk = g.item() if hasattr(g, "item") else g
                st = groups.setdefault(gk, [a.init() for a in aggs])
                for ai, a in enumerate(aggs):
                    col = sub[a.on] if a.on else np.empty(B.num_rows(sub))
                    st[ai] = a.accumulate_block(st[ai], col)
        rows = []
        for gk in sorted(groups):
            row = {key: gk}
            for a, s in zip(aggs, groups[gk]):
                row[a.name] = a.finalize(s)
            rows.append(row)
        return B.from_rows(rows)

    return ShuffleOp(
        map_fn=map_fn, reduce_fn=reduce_fn,
        sample_fn=_key_sample_fn(key, sample_rows), aux_fn=aux_fn,
        name=f"Shuffle(groupby[{key}])",
    )


def map_groups_op(key: str, fn: Callable[[B.Block], Any], *,
                  sample_rows: int = 64):
    from ray_tpu.data.plan import ShuffleOp

    def aux_fn(samples, _metas, P):
        return _range_boundaries(samples, P)

    def map_fn(blk, _i, P, aux):
        return _range_partition(blk, P, aux, key)

    def reduce_fn(pieces, _r, _aux):
        from ray_tpu.data.dataset import _coerce_batch

        full = B.concat(pieces)
        if not B.num_rows(full):
            return full
        keys = np.asarray(B.column_numpy(full, key))
        out: List[B.Block] = []
        for g in np.unique(keys):
            sub = B.ensure_numpy(
                B.take_indices(full, np.nonzero(keys == g)[0])
            )
            out.append(_coerce_batch(fn(sub)))
        return B.concat(out)

    return ShuffleOp(
        map_fn=map_fn, reduce_fn=reduce_fn,
        sample_fn=_key_sample_fn(key, sample_rows), aux_fn=aux_fn,
        name=f"Shuffle(map_groups[{key}])",
    )
