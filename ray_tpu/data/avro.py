"""Minimal Avro object-container-file reader (no external deps).

Reference: `python/ray/data/_internal/datasource/avro_datasource.py`
(which wraps the `fastavro` package).  This is a native decoder for the
common subset: container files with `null` or `deflate` codecs, and
schemas composed of primitives, records, arrays, maps, unions, enums,
and fixed — enough for the files data pipelines actually exchange.

Format (Avro 1.11 spec): header `Obj\x01` + metadata map (schema JSON,
codec) + 16-byte sync marker, then blocks of
`<count><byte-size><records><sync>` with zigzag-varint framing.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Any, Dict, List, Tuple

MAGIC = b"Obj\x01"


class _Reader:
    def __init__(self, data: bytes):
        self.buf = data
        self.pos = 0

    def read(self, n: int) -> bytes:
        out = self.buf[self.pos:self.pos + n]
        if len(out) != n:
            raise ValueError("truncated avro data")
        self.pos += n
        return out

    def at_end(self) -> bool:
        return self.pos >= len(self.buf)

    # -- primitives -----------------------------------------------------
    def long(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)  # zigzag

    def bytes_(self) -> bytes:
        return self.read(self.long())

    def string(self) -> str:
        return self.bytes_().decode()

    def float_(self) -> float:
        return struct.unpack("<f", self.read(4))[0]

    def double(self) -> float:
        return struct.unpack("<d", self.read(8))[0]

    def boolean(self) -> bool:
        return self.read(1) != b"\x00"


def _decode(r: _Reader, schema: Any, named: Dict[str, Any]) -> Any:
    if isinstance(schema, str):
        s = schema
        if s == "null":
            return None
        if s == "boolean":
            return r.boolean()
        if s in ("int", "long"):
            return r.long()
        if s == "float":
            return r.float_()
        if s == "double":
            return r.double()
        if s == "bytes":
            return r.bytes_()
        if s == "string":
            return r.string()
        if s in named:  # named-type reference
            return _decode(r, named[s], named)
        raise ValueError(f"unsupported avro type {s!r}")
    if isinstance(schema, list):  # union: branch index then value
        return _decode(r, schema[r.long()], named)
    t = schema["type"]
    if t == "record":
        named[schema["name"]] = schema
        return {
            f["name"]: _decode(r, f["type"], named)
            for f in schema["fields"]
        }
    if t == "array":
        out: List[Any] = []
        while True:
            n = r.long()
            if n == 0:
                return out
            if n < 0:  # block with byte size
                n = -n
                r.long()
            for _ in range(n):
                out.append(_decode(r, schema["items"], named))
    if t == "map":
        m: Dict[str, Any] = {}
        while True:
            n = r.long()
            if n == 0:
                return m
            if n < 0:
                n = -n
                r.long()
            for _ in range(n):
                m[r.string()] = _decode(r, schema["values"], named)
    if t == "enum":
        named[schema["name"]] = schema
        return schema["symbols"][r.long()]
    if t == "fixed":
        named[schema["name"]] = schema
        return r.read(schema["size"])
    # {"type": "string"} style wrappers
    if isinstance(t, (str, list, dict)):
        return _decode(r, t, named)
    raise ValueError(f"unsupported avro schema {schema!r}")


def read_avro_rows(path: str) -> List[Dict[str, Any]]:
    with open(path, "rb") as f:
        data = f.read()
    r = _Reader(data)
    if r.read(4) != MAGIC:
        raise ValueError(f"{path} is not an avro container file")
    meta: Dict[str, bytes] = {}
    while True:
        n = r.long()
        if n == 0:
            break
        if n < 0:
            n = -n
            r.long()
        for _ in range(n):
            key = r.string()
            meta[key] = r.bytes_()
    schema = json.loads(meta["avro.schema"])
    codec = meta.get("avro.codec", b"null").decode()
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported avro codec {codec!r}")
    sync = r.read(16)
    rows: List[Dict[str, Any]] = []
    named: Dict[str, Any] = {}
    while not r.at_end():
        count = r.long()
        size = r.long()
        payload = r.read(size)
        if codec == "deflate":
            payload = zlib.decompress(payload, -15)
        br = _Reader(payload)
        for _ in range(count):
            row = _decode(br, schema, named)
            rows.append(row if isinstance(row, dict) else {"value": row})
        if r.read(16) != sync:
            raise ValueError(f"avro sync marker mismatch in {path}")
    return rows
