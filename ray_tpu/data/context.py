"""Execution context for Data pipelines.

Reference: `data/context.py` DataContext + the execution resource
manager / backpressure policies
(`_internal/execution/resource_manager.py:25`,
`backpressure_policy/`).  One process-wide current context, overridable
per call the way the reference does.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class DataContext:
    #: max in-flight tasks per streaming stage (count-based pressure)
    window: int = 8
    #: max estimated bytes being processed per stage at once
    #: (byte-based pressure; estimated from input-block metadata when
    #: the upstream task has completed)
    max_stage_inflight_bytes: int = 256 * 1024 * 1024
    #: pipelined calls per actor in actor-pool map stages
    actor_pool_pipeline_depth: int = 2
    #: retries for every data-plane task (read/map/shuffle map+reduce):
    #: a SIGKILLed worker retries through the core worker-died path and
    #: lost output blocks re-derive via lineage reconstruction, so one
    #: dead worker never costs an epoch
    data_task_max_retries: int = 4
    #: hard bound on how long an admission point may block making zero
    #: progress before surfacing a typed BackPressureError (never an
    #: unbounded queue, never a silent hang)
    backpressure_timeout_s: float = 120.0
    #: rows sampled per input block when a shuffle needs range
    #: boundaries (sort / groupby)
    shuffle_sample_rows: int = 64
    #: fraction of the node's object-store budget a stage may hold
    #: in flight (pinned inputs + outputs of running tasks).  The
    #: effective per-stage byte cap is
    #: min(max_stage_inflight_bytes, fraction * store_capacity) — the
    #: reference resource manager budgets operator memory against the
    #: store the same way, which is what lets an over-memory shuffle
    #: complete via spilling instead of wedging on pinned bytes
    store_memory_fraction: float = 0.25
    #: override the reduce-partition count for shuffles (None: one
    #: partition per input block; repartition always uses its target)
    shuffle_partitions: Optional[int] = None

    @staticmethod
    def get_current() -> "DataContext":
        global _current_context
        if _current_context is None:
            _current_context = DataContext()
        return _current_context


_current_context: Optional[DataContext] = None


@dataclasses.dataclass
class ActorPoolStrategy:
    """compute= strategy for `map_batches` with a class UDF: a pool of
    actors holding one constructed UDF instance each, autoscaled
    between min_size and max_size by queue pressure (reference:
    `actor_pool_map_operator.py` + `execution/autoscaler/`)."""

    size: Optional[int] = None  # fixed size shorthand
    min_size: int = 1
    max_size: Optional[int] = None

    def __post_init__(self):
        if self.size is not None:
            self.min_size = self.max_size = self.size
        if self.max_size is None:
            self.max_size = max(self.min_size, 4)
        if self.min_size < 1 or self.max_size < self.min_size:
            raise ValueError(
                f"invalid actor pool bounds [{self.min_size}, {self.max_size}]"
            )
