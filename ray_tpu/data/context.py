"""Execution context for Data pipelines.

Reference: `data/context.py` DataContext + the execution resource
manager / backpressure policies
(`_internal/execution/resource_manager.py:25`,
`backpressure_policy/`).  One process-wide current context, overridable
per call the way the reference does.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class DataContext:
    #: max in-flight tasks per streaming stage (count-based pressure)
    window: int = 8
    #: max estimated bytes being processed per stage at once
    #: (byte-based pressure; estimated from input-block metadata when
    #: the upstream task has completed)
    max_stage_inflight_bytes: int = 256 * 1024 * 1024
    #: pipelined calls per actor in actor-pool map stages
    actor_pool_pipeline_depth: int = 2

    @staticmethod
    def get_current() -> "DataContext":
        global _current_context
        if _current_context is None:
            _current_context = DataContext()
        return _current_context


_current_context: Optional[DataContext] = None


@dataclasses.dataclass
class ActorPoolStrategy:
    """compute= strategy for `map_batches` with a class UDF: a pool of
    actors holding one constructed UDF instance each, autoscaled
    between min_size and max_size by queue pressure (reference:
    `actor_pool_map_operator.py` + `execution/autoscaler/`)."""

    size: Optional[int] = None  # fixed size shorthand
    min_size: int = 1
    max_size: Optional[int] = None

    def __post_init__(self):
        if self.size is not None:
            self.min_size = self.max_size = self.size
        if self.max_size is None:
            self.max_size = max(self.min_size, 4)
        if self.min_size < 1 or self.max_size < self.min_size:
            raise ValueError(
                f"invalid actor pool bounds [{self.min_size}, {self.max_size}]"
            )
