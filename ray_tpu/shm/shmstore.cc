// Per-node shared-memory object store.
//
// Capability-equivalent of the reference's plasma store
// (`src/ray/object_manager/plasma/store.h:55`): a node-local arena of
// immutable objects with create/seal/get/release lifecycle, pinning,
// and LRU eviction of sealed-unpinned objects
// (`plasma/eviction_policy.h`, `object_lifecycle_manager.h`).
//
// Architectural departure from plasma (deliberate, TPU-first): plasma is
// a daemon which clients talk to over a unix socket with fd-passing
// (`plasma/fling.h`); here the *entire store state lives inside the
// shared-memory segment* — object table, allocator free list, and a
// process-shared robust mutex — so every process on the node (workers,
// node daemon, driver) maps the segment once and performs metadata
// operations directly, with no per-op IPC.  On a TPU host the store only
// carries host-side data (batches, checkpoints metadata, pickled
// results); device arrays stay resident on the TPU and never pass
// through it.
//
// Concurrency: one robust process-shared mutex guards the table +
// allocator; a process-shared condvar broadcasts seals so blocking Get
// can wait without polling.  If a process dies while holding the lock
// the next locker recovers via EOWNERDEAD and makes the state
// consistent.
//
// Build: g++ -O2 -shared -fPIC -o libshmstore.so shmstore.cc -lpthread -lrt

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <new>

extern "C" {

int rts_create_ex(void* hv, const uint8_t* id, uint64_t size, uint64_t* out_off,
                  int allow_evict);

#define RTS_OK 0
#define RTS_EXISTS (-1)
#define RTS_NOT_FOUND (-2)
#define RTS_OOM (-3)
#define RTS_TIMEOUT (-4)
#define RTS_BAD_STATE (-5)
#define RTS_IO (-6)

static const uint64_t kMagic = 0x5254535348'4d0001ULL;  // "RTSSHM" v1
static const uint64_t kAlign = 64;
static const int kIdLen = 24;  // padded; ObjectID is 18 bytes

enum EntryState : uint8_t {
  ENTRY_FREE = 0,
  ENTRY_CREATED = 1,
  ENTRY_SEALED = 2,
  ENTRY_TOMBSTONE = 3,  // deleted slot, keeps probe chains intact
};

struct Entry {
  uint8_t id[kIdLen];
  uint8_t state;
  uint8_t pad_[3];
  uint32_t pins;
  uint64_t off;    // data offset from segment base
  uint64_t size;   // payload bytes
  uint64_t alloc;  // bytes actually taken from the arena (>= size)
  uint64_t lru;    // last-touch tick
  uint64_t creator_pid;
};

// Free blocks form an address-ordered doubly-linked list threaded
// through the arena itself (offsets, not pointers — every process maps
// the segment at a different base address).
struct FreeBlock {
  uint64_t size;
  uint64_t next;  // offset of next free block, 0 = none
  uint64_t prev;
};

struct Header {
  uint64_t magic;
  uint64_t segment_size;
  uint64_t table_cap;  // power of two
  uint64_t table_off;
  uint64_t arena_off;
  uint64_t arena_size;
  pthread_mutex_t mu;
  pthread_cond_t cv;
  uint64_t lru_tick;
  uint64_t used_bytes;
  uint64_t num_objects;
  uint64_t free_head;  // offset of first free block
  uint64_t num_evictions;
  uint64_t bytes_evicted;
};

struct Handle {
  uint8_t* base;
  Header* hdr;
  Entry* table;
  uint64_t mapped_size;  // actual mmap length (don't trust hdr on teardown)
  int fd;
  char name[256];
};

static uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

static uint64_t id_hash(const uint8_t* id) {
  // FNV-1a over the 18 significant bytes.
  uint64_t h = 1469598103934665603ULL;
  for (int i = 0; i < 18; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

static void lock(Header* hdr) {
  int rc = pthread_mutex_lock(&hdr->mu);
  if (rc == EOWNERDEAD) {
    // A process died holding the lock.  Table/allocator mutations below
    // are each small and idempotent-ish; mark consistent and continue —
    // worst case an object leaks until deleted by its owner's GC.
    pthread_mutex_consistent(&hdr->mu);
  }
}

static void unlock(Header* hdr) { pthread_mutex_unlock(&hdr->mu); }

static Entry* find_entry(Handle* h, const uint8_t* id) {
  uint64_t mask = h->hdr->table_cap - 1;
  uint64_t i = id_hash(id) & mask;
  for (uint64_t probe = 0; probe <= mask; probe++, i = (i + 1) & mask) {
    Entry* e = &h->table[i];
    if (e->state == ENTRY_FREE) return nullptr;
    if (e->state != ENTRY_TOMBSTONE && memcmp(e->id, id, 18) == 0) return e;
  }
  return nullptr;
}

static Entry* find_slot(Handle* h, const uint8_t* id) {
  uint64_t mask = h->hdr->table_cap - 1;
  uint64_t i = id_hash(id) & mask;
  Entry* first_tomb = nullptr;
  for (uint64_t probe = 0; probe <= mask; probe++, i = (i + 1) & mask) {
    Entry* e = &h->table[i];
    if (e->state == ENTRY_FREE) return first_tomb ? first_tomb : e;
    if (e->state == ENTRY_TOMBSTONE) {
      if (!first_tomb) first_tomb = e;
    } else if (memcmp(e->id, id, 18) == 0) {
      return e;  // existing
    }
  }
  return first_tomb;  // table full of tombstones/live
}

// ---- allocator ------------------------------------------------------

static FreeBlock* fb(Handle* h, uint64_t off) {
  return reinterpret_cast<FreeBlock*>(h->base + off);
}

// Allocate nbytes from the free list (first fit, address ordered).
// Returns offset or 0 on failure; *actual receives the bytes really
// taken (may exceed the request when a whole block is consumed).
static uint64_t arena_alloc(Handle* h, uint64_t nbytes, uint64_t* actual) {
  Header* hdr = h->hdr;
  nbytes = align_up(nbytes < sizeof(FreeBlock) ? sizeof(FreeBlock) : nbytes, kAlign);
  uint64_t off = hdr->free_head;
  while (off) {
    FreeBlock* b = fb(h, off);
    if (b->size >= nbytes) {
      uint64_t rem = b->size - nbytes;
      if (rem >= kAlign + sizeof(FreeBlock)) {
        // split: tail remains free
        uint64_t tail_off = off + nbytes;
        FreeBlock* tail = fb(h, tail_off);
        tail->size = rem;
        tail->next = b->next;
        tail->prev = b->prev;
        if (b->prev)
          fb(h, b->prev)->next = tail_off;
        else
          hdr->free_head = tail_off;
        if (b->next) fb(h, b->next)->prev = tail_off;
      } else {
        nbytes = b->size;  // take whole block
        if (b->prev)
          fb(h, b->prev)->next = b->next;
        else
          hdr->free_head = b->next;
        if (b->next) fb(h, b->next)->prev = b->prev;
      }
      hdr->used_bytes += nbytes;
      *actual = nbytes;
      return off;
    }
    off = b->next;
  }
  return 0;
}

// Free [off, off+size) back into the address-ordered list, coalescing
// with adjacent free blocks.
static void arena_free(Handle* h, uint64_t off, uint64_t size) {
  Header* hdr = h->hdr;
  size = align_up(size < sizeof(FreeBlock) ? sizeof(FreeBlock) : size, kAlign);
  hdr->used_bytes -= size;
  // find insertion point (prev < off < next)
  uint64_t prev = 0, next = hdr->free_head;
  while (next && next < off) {
    prev = next;
    next = fb(h, next)->next;
  }
  uint64_t blk_off = off;
  uint64_t blk_size = size;
  // coalesce with prev
  if (prev && prev + fb(h, prev)->size == off) {
    blk_off = prev;
    blk_size += fb(h, prev)->size;
    prev = fb(h, prev)->prev;
    // prev now points before the merged block; relink below rebuilds
  }
  // coalesce with next
  if (next && blk_off + blk_size == next) {
    blk_size += fb(h, next)->size;
    next = fb(h, next)->next;
  }
  FreeBlock* b = fb(h, blk_off);
  b->size = blk_size;
  b->prev = prev;
  b->next = next;
  if (prev)
    fb(h, prev)->next = blk_off;
  else
    hdr->free_head = blk_off;
  if (next) fb(h, next)->prev = blk_off;
}

// Evict the single LRU sealed+unpinned object.  Caller holds the lock.
// Mirrors plasma's eviction policy (`plasma/eviction_policy.h`): only
// sealed, unreferenced objects are evictable.  Returns 1 if something
// was evicted, 0 if nothing is evictable.
static int evict_one(Handle* h) {
  Header* hdr = h->hdr;
  Entry* victim = nullptr;
  for (uint64_t i = 0; i < hdr->table_cap; i++) {
    Entry* e = &h->table[i];
    if (e->state == ENTRY_SEALED && e->pins == 0) {
      if (!victim || e->lru < victim->lru) victim = e;
    }
  }
  if (!victim) return 0;
  arena_free(h, victim->off, victim->alloc);
  victim->state = ENTRY_TOMBSTONE;
  hdr->num_objects--;
  hdr->num_evictions++;
  hdr->bytes_evicted += victim->size;
  return 1;
}

// ---- lifecycle ------------------------------------------------------

static Handle* map_segment(const char* name, int create, uint64_t segment_size) {
  int flags = create ? (O_RDWR | O_CREAT | O_EXCL) : O_RDWR;
  int fd = shm_open(name, flags, 0600);
  if (fd < 0) return nullptr;
  if (create) {
    if (ftruncate(fd, (off_t)segment_size) != 0) {
      close(fd);
      shm_unlink(name);
      return nullptr;
    }
  } else {
    struct stat st;
    if (fstat(fd, &st) != 0) {
      close(fd);
      return nullptr;
    }
    segment_size = (uint64_t)st.st_size;
  }
  void* base = mmap(nullptr, segment_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Handle* h = new Handle();
  h->base = (uint8_t*)base;
  h->hdr = (Header*)base;
  h->mapped_size = segment_size;
  h->fd = fd;
  snprintf(h->name, sizeof(h->name), "%s", name);
  return h;
}

void* rts_create_store(const char* name, uint64_t capacity, uint64_t table_cap) {
  if (table_cap == 0) table_cap = 1 << 16;
  // round table_cap up to power of two
  uint64_t tc = 1;
  while (tc < table_cap) tc <<= 1;
  table_cap = tc;

  uint64_t hdr_size = align_up(sizeof(Header), kAlign);
  uint64_t table_size = align_up(table_cap * sizeof(Entry), kAlign);
  uint64_t arena_size = align_up(capacity, kAlign);
  uint64_t segment_size = hdr_size + table_size + arena_size;

  Handle* h = map_segment(name, 1, segment_size);
  if (!h) return nullptr;

  Header* hdr = h->hdr;
  memset(hdr, 0, sizeof(Header));
  hdr->segment_size = segment_size;
  hdr->table_cap = table_cap;
  hdr->table_off = hdr_size;
  hdr->arena_off = hdr_size + table_size;
  hdr->arena_size = arena_size;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_condattr_setclock(&ca, CLOCK_MONOTONIC);
  pthread_cond_init(&hdr->cv, &ca);

  memset(h->base + hdr->table_off, 0, table_size);
  h->table = (Entry*)(h->base + hdr->table_off);

  // one big free block
  FreeBlock* b = fb(h, hdr->arena_off);
  b->size = arena_size;
  b->next = 0;
  b->prev = 0;
  hdr->free_head = hdr->arena_off;

  __sync_synchronize();
  hdr->magic = kMagic;
  return h;
}

void* rts_open_store(const char* name) {
  Handle* h = map_segment(name, 0, 0);
  if (!h) return nullptr;
  // wait briefly for creator to finish init
  for (int i = 0; i < 1000 && h->hdr->magic != kMagic; i++) usleep(1000);
  if (h->hdr->magic != kMagic) {
    munmap(h->base, h->mapped_size);
    close(h->fd);
    delete h;
    return nullptr;
  }
  h->table = (Entry*)(h->base + h->hdr->table_off);
  return h;
}

int rts_close(void* hv) {
  Handle* h = (Handle*)hv;
  munmap(h->base, h->mapped_size);
  close(h->fd);
  delete h;
  return RTS_OK;
}

int rts_unlink(const char* name) { return shm_unlink(name) == 0 ? RTS_OK : RTS_IO; }

// ---- object ops -----------------------------------------------------

int rts_create(void* hv, const uint8_t* id, uint64_t size, uint64_t* out_off) {
  return rts_create_ex(hv, id, size, out_off, 1);
}

// allow_evict=0: never destroy sealed primaries to make room — the
// caller's backpressure path spills them to disk instead (reference:
// create_request_queue.h queues creates and triggers spilling rather
// than evicting unconditionally).
int rts_create_ex(void* hv, const uint8_t* id, uint64_t size, uint64_t* out_off,
                  int allow_evict) {
  Handle* h = (Handle*)hv;
  Header* hdr = h->hdr;
  lock(hdr);
  Entry* existing = find_entry(h, id);
  if (existing) {
    unlock(hdr);
    return RTS_EXISTS;
  }
  // Evict-until-fit (only when allowed): retry after each eviction so
  // fragmentation is resolved by coalescing, not just total-free math.
  uint64_t alloc_size = 0;
  uint64_t off = arena_alloc(h, size, &alloc_size);
  while (!off) {
    if (!allow_evict || !evict_one(h)) {
      unlock(hdr);
      return RTS_OOM;
    }
    off = arena_alloc(h, size, &alloc_size);
  }
  Entry* e = find_slot(h, id);
  if (!e) {
    arena_free(h, off, alloc_size);
    unlock(hdr);
    return RTS_OOM;  // table full
  }
  memcpy(e->id, id, 18);
  memset(e->id + 18, 0, kIdLen - 18);
  e->state = ENTRY_CREATED;
  e->pins = 1;  // creator holds a pin until seal
  e->off = off;
  e->size = size;
  e->alloc = alloc_size;
  e->lru = ++hdr->lru_tick;
  e->creator_pid = (uint64_t)getpid();
  hdr->num_objects++;
  unlock(hdr);
  *out_off = off;
  return RTS_OK;
}

int rts_seal(void* hv, const uint8_t* id) {
  Handle* h = (Handle*)hv;
  Header* hdr = h->hdr;
  lock(hdr);
  Entry* e = find_entry(h, id);
  if (!e) {
    unlock(hdr);
    return RTS_NOT_FOUND;
  }
  if (e->state != ENTRY_CREATED) {
    unlock(hdr);
    return RTS_BAD_STATE;
  }
  e->state = ENTRY_SEALED;
  if (e->pins > 0) e->pins--;  // drop creator pin
  e->lru = ++hdr->lru_tick;
  pthread_cond_broadcast(&hdr->cv);
  unlock(hdr);
  return RTS_OK;
}

int rts_get(void* hv, const uint8_t* id, int64_t timeout_ms, uint64_t* out_off,
            uint64_t* out_size) {
  Handle* h = (Handle*)hv;
  Header* hdr = h->hdr;
  struct timespec deadline;
  if (timeout_ms > 0) {
    clock_gettime(CLOCK_MONOTONIC, &deadline);
    deadline.tv_sec += timeout_ms / 1000;
    deadline.tv_nsec += (timeout_ms % 1000) * 1000000L;
    if (deadline.tv_nsec >= 1000000000L) {
      deadline.tv_sec++;
      deadline.tv_nsec -= 1000000000L;
    }
  }
  lock(hdr);
  for (;;) {
    Entry* e = find_entry(h, id);
    if (e && e->state == ENTRY_SEALED) {
      e->pins++;
      e->lru = ++hdr->lru_tick;
      *out_off = e->off;
      *out_size = e->size;
      unlock(hdr);
      return RTS_OK;
    }
    if (timeout_ms == 0) {
      unlock(hdr);
      return e ? RTS_BAD_STATE : RTS_NOT_FOUND;
    }
    int rc;
    if (timeout_ms < 0) {
      rc = pthread_cond_wait(&hdr->cv, &hdr->mu);
    } else {
      rc = pthread_cond_timedwait(&hdr->cv, &hdr->mu, &deadline);
    }
    if (rc == ETIMEDOUT) {
      unlock(hdr);
      return RTS_TIMEOUT;
    }
    if (rc == EOWNERDEAD) pthread_mutex_consistent(&hdr->mu);
  }
}

int rts_release(void* hv, const uint8_t* id) {
  Handle* h = (Handle*)hv;
  lock(h->hdr);
  Entry* e = find_entry(h, id);
  if (!e) {
    unlock(h->hdr);
    return RTS_NOT_FOUND;
  }
  if (e->pins > 0) e->pins--;
  unlock(h->hdr);
  return RTS_OK;
}

int rts_delete(void* hv, const uint8_t* id) {
  Handle* h = (Handle*)hv;
  Header* hdr = h->hdr;
  lock(hdr);
  Entry* e = find_entry(h, id);
  if (!e) {
    unlock(hdr);
    return RTS_NOT_FOUND;
  }
  if (e->pins > 0) {
    // Pinned (including the creator pin on unsealed objects): refuse —
    // freeing here would be a use-after-free for the pin holder.
    unlock(hdr);
    return RTS_BAD_STATE;
  }
  arena_free(h, e->off, e->alloc);
  e->state = ENTRY_TOMBSTONE;
  hdr->num_objects--;
  unlock(hdr);
  return RTS_OK;
}

int rts_contains(void* hv, const uint8_t* id) {
  Handle* h = (Handle*)hv;
  lock(h->hdr);
  Entry* e = find_entry(h, id);
  int r = (e && e->state == ENTRY_SEALED) ? 1 : 0;
  unlock(h->hdr);
  return r;
}

// Delete every object created by a now-dead process that was never
// sealed (orphan cleanup after a worker crash).
int rts_reap_creator(void* hv, uint64_t pid) {
  Handle* h = (Handle*)hv;
  Header* hdr = h->hdr;
  int n = 0;
  lock(hdr);
  for (uint64_t i = 0; i < hdr->table_cap; i++) {
    Entry* e = &h->table[i];
    if (e->state == ENTRY_CREATED && e->creator_pid == pid) {
      arena_free(h, e->off, e->alloc);
      e->state = ENTRY_TOMBSTONE;
      hdr->num_objects--;
      n++;
    }
  }
  unlock(hdr);
  return n;
}

// LRU-ordered ids of spillable (sealed, unpinned) objects.  The spill
// manager reads candidates, persists them to disk, then deletes them —
// the disk-spilling path the reference's LocalObjectManager drives
// (`local_object_manager.h:110` SpillObjects).  out receives up to
// max_ids contiguous 18-byte ids; returns the count written.
uint64_t rts_spill_candidates(void* hv, uint8_t* out, uint64_t max_ids) {
  Handle* h = (Handle*)hv;
  Header* hdr = h->hdr;
  lock(hdr);
  // selection sort over a bounded output: table scans are O(cap) and
  // cap is 64k — fine at the 1 Hz spill cadence
  uint64_t n = 0;
  uint64_t last_lru = 0;
  while (n < max_ids) {
    Entry* best = nullptr;
    for (uint64_t i = 0; i < hdr->table_cap; i++) {
      Entry* e = &h->table[i];
      if (e->state != ENTRY_SEALED || e->pins != 0) continue;
      if (e->lru < last_lru) continue;
      if (e->lru == last_lru && n > 0) continue;  // already emitted
      if (!best || e->lru < best->lru) best = e;
    }
    if (!best) break;
    memcpy(out + n * 18, best->id, 18);
    last_lru = best->lru;
    n++;
  }
  unlock(hdr);
  return n;
}

uint64_t rts_used(void* hv) { return ((Handle*)hv)->hdr->used_bytes; }
uint64_t rts_capacity(void* hv) { return ((Handle*)hv)->hdr->arena_size; }
uint64_t rts_count(void* hv) { return ((Handle*)hv)->hdr->num_objects; }
uint64_t rts_evictions(void* hv) { return ((Handle*)hv)->hdr->num_evictions; }

// ---- mutable channels ------------------------------------------------
//
// Native substrate for compiled-DAG channels, the design of the
// reference's mutable objects (`experimental_mutable_object_manager.h:48`
// WriteAcquire:153 / ReadAcquire / ReadRelease): one fixed shm region
// per channel with writer/reader acquire-release over a ring of slots.
// Unlike the per-message create/seal/get/delete path through the object
// table, a channel does ZERO allocation per message — the writer
// serializes straight into its slot, publication is a seq bump +
// condvar broadcast, and the reader's release hands the slot back.
// SPSC by contract (one producer, one consumer per channel), which is
// exactly the compiled-DAG topology.
//
// The channel region is an ordinary arena allocation registered in the
// object table as a pinned sealed entry, so eviction/spilling never
// touches it and teardown is a plain delete.

struct ChanSlot {
  uint64_t size;
  uint32_t kind;
  uint32_t pad_;
};

struct ChanHeader {
  uint64_t magic;  // kChanMagic
  pthread_mutex_t mu;
  pthread_cond_t cv;
  uint64_t nslots;
  uint64_t slot_size;
  uint64_t write_seq;  // published messages
  uint64_t read_seq;   // consumed messages
  uint32_t closed;
  uint32_t pad_;
  // ChanSlot[nslots] follows, then payloads (each slot_size, aligned)
};

static const uint64_t kChanMagic = 0x525453434841'4eULL;  // "RTSCHAN"

static ChanSlot* chan_slots(uint8_t* ch) {
  return reinterpret_cast<ChanSlot*>(ch + sizeof(ChanHeader));
}

static uint64_t chan_payload_off(ChanHeader* c, uint64_t slot) {
  uint64_t meta = align_up(sizeof(ChanHeader) + c->nslots * sizeof(ChanSlot), kAlign);
  return meta + slot * align_up(c->slot_size, kAlign);
}

static uint64_t chan_region_bytes(uint64_t nslots, uint64_t slot_size) {
  return align_up(sizeof(ChanHeader) + nslots * sizeof(ChanSlot), kAlign) +
         nslots * align_up(slot_size, kAlign);
}

static ChanHeader* chan_of(Handle* h, const uint8_t* id, uint64_t* base_off) {
  Entry* e = find_entry(h, id);
  if (!e || e->state != ENTRY_SEALED) return nullptr;
  ChanHeader* c = reinterpret_cast<ChanHeader*>(h->base + e->off);
  if (c->magic != kChanMagic) return nullptr;
  if (base_off) *base_off = e->off;
  return c;
}

static void chan_lock(ChanHeader* c) {
  if (pthread_mutex_lock(&c->mu) == EOWNERDEAD) pthread_mutex_consistent(&c->mu);
}

// Opener side of the race: the creating peer's entry exists but may
// still be ENTRY_CREATED (header not yet initialized).  A blocking get
// waits for the seal (rts_seal broadcasts), then the pin is returned —
// the creator's create-time pin is the one that keeps the region alive.
static int chan_wait_ready(void* hv, const uint8_t* id) {
  uint64_t o, s;
  int rc = rts_get(hv, id, /*timeout_ms=*/10000, &o, &s);
  if (rc != RTS_OK) return rc;
  rts_release(hv, id);
  return RTS_EXISTS;
}

int rts_chan_create(void* hv, const uint8_t* id, uint64_t nslots,
                    uint64_t slot_size) {
  Handle* h = (Handle*)hv;
  Header* hdr = h->hdr;
  lock(hdr);
  bool exists = find_entry(h, id) != nullptr;
  unlock(hdr);
  if (exists) return chan_wait_ready(hv, id);
  uint64_t bytes = chan_region_bytes(nslots, slot_size);
  uint64_t off;
  int rc = rts_create_ex(hv, id, bytes, &off, /*allow_evict=*/0);
  if (rc == RTS_EXISTS) return chan_wait_ready(hv, id);
  if (rc != RTS_OK) return rc;
  ChanHeader* c = reinterpret_cast<ChanHeader*>(h->base + off);
  memset(c, 0, sizeof(ChanHeader));
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&c->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_condattr_setclock(&ca, CLOCK_MONOTONIC);
  pthread_cond_init(&c->cv, &ca);
  c->nslots = nslots;
  c->slot_size = slot_size;
  c->magic = kChanMagic;
  rc = rts_seal(hv, id);
  if (rc != RTS_OK) return rc;
  // pin forever (until delete): the channel must never be evicted
  uint64_t o, s;
  return rts_get(hv, id, 0, &o, &s);
}

static void chan_deadline(struct timespec* ts, int64_t timeout_ms) {
  clock_gettime(CLOCK_MONOTONIC, ts);
  ts->tv_sec += timeout_ms / 1000;
  ts->tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec++;
    ts->tv_nsec -= 1000000000L;
  }
}

// Writer: block until a slot is free, return payload offset to fill.
int rts_chan_write_acquire(void* hv, const uint8_t* id, int64_t timeout_ms,
                           uint64_t* out_off, uint64_t* out_cap) {
  Handle* h = (Handle*)hv;
  uint64_t base_off;
  ChanHeader* c = chan_of(h, id, &base_off);
  if (!c) return RTS_NOT_FOUND;
  struct timespec dl;
  if (timeout_ms > 0) chan_deadline(&dl, timeout_ms);
  chan_lock(c);
  for (;;) {
    if (c->closed) {
      pthread_mutex_unlock(&c->mu);
      return RTS_BAD_STATE;
    }
    if (c->write_seq - c->read_seq < c->nslots) {
      uint64_t slot = c->write_seq % c->nslots;
      *out_off = base_off + chan_payload_off(c, slot);
      *out_cap = c->slot_size;
      pthread_mutex_unlock(&c->mu);
      return RTS_OK;
    }
    int rc;
    if (timeout_ms < 0) {
      rc = pthread_cond_wait(&c->cv, &c->mu);
    } else if (timeout_ms == 0) {
      pthread_mutex_unlock(&c->mu);
      return RTS_TIMEOUT;
    } else {
      rc = pthread_cond_timedwait(&c->cv, &c->mu, &dl);
    }
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&c->mu);
      return RTS_TIMEOUT;
    }
    if (rc == EOWNERDEAD) pthread_mutex_consistent(&c->mu);
  }
}

// Writer: publish the acquired slot.
int rts_chan_write_seal(void* hv, const uint8_t* id, uint64_t size,
                        uint32_t kind) {
  Handle* h = (Handle*)hv;
  ChanHeader* c = chan_of(h, id, nullptr);
  if (!c) return RTS_NOT_FOUND;
  if (size > c->slot_size) return RTS_OOM;
  chan_lock(c);
  uint64_t slot = c->write_seq % c->nslots;
  ChanSlot* s = &chan_slots(reinterpret_cast<uint8_t*>(c))[slot];
  s->size = size;
  s->kind = kind;
  c->write_seq++;
  pthread_cond_broadcast(&c->cv);
  pthread_mutex_unlock(&c->mu);
  return RTS_OK;
}

// Reader: block until a message is published; returns payload location.
int rts_chan_read_acquire(void* hv, const uint8_t* id, int64_t timeout_ms,
                          uint64_t* out_off, uint64_t* out_size,
                          uint32_t* out_kind) {
  Handle* h = (Handle*)hv;
  uint64_t base_off;
  ChanHeader* c = chan_of(h, id, &base_off);
  if (!c) return RTS_NOT_FOUND;
  struct timespec dl;
  if (timeout_ms > 0) chan_deadline(&dl, timeout_ms);
  chan_lock(c);
  for (;;) {
    if (c->read_seq < c->write_seq) {
      uint64_t slot = c->read_seq % c->nslots;
      ChanSlot* s = &chan_slots(reinterpret_cast<uint8_t*>(c))[slot];
      *out_off = base_off + chan_payload_off(c, slot);
      *out_size = s->size;
      *out_kind = s->kind;
      pthread_mutex_unlock(&c->mu);
      return RTS_OK;
    }
    if (c->closed) {
      pthread_mutex_unlock(&c->mu);
      return RTS_BAD_STATE;
    }
    int rc;
    if (timeout_ms < 0) {
      rc = pthread_cond_wait(&c->cv, &c->mu);
    } else if (timeout_ms == 0) {
      pthread_mutex_unlock(&c->mu);
      return RTS_TIMEOUT;
    } else {
      rc = pthread_cond_timedwait(&c->cv, &c->mu, &dl);
    }
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&c->mu);
      return RTS_TIMEOUT;
    }
    if (rc == EOWNERDEAD) pthread_mutex_consistent(&c->mu);
  }
}

// Reader: consume the acquired message (slot returns to the writer).
int rts_chan_read_release(void* hv, const uint8_t* id) {
  Handle* h = (Handle*)hv;
  ChanHeader* c = chan_of(h, id, nullptr);
  if (!c) return RTS_NOT_FOUND;
  chan_lock(c);
  c->read_seq++;
  pthread_cond_broadcast(&c->cv);
  pthread_mutex_unlock(&c->mu);
  return RTS_OK;
}

// Either endpoint: mark closed; blocked/future acquires fail BAD_STATE
// (readers drain published messages first).
int rts_chan_close(void* hv, const uint8_t* id) {
  Handle* h = (Handle*)hv;
  ChanHeader* c = chan_of(h, id, nullptr);
  if (!c) return RTS_NOT_FOUND;
  chan_lock(c);
  c->closed = 1;
  pthread_cond_broadcast(&c->cv);
  pthread_mutex_unlock(&c->mu);
  return RTS_OK;
}

}  // extern "C"
