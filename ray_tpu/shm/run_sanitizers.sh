#!/bin/bash
# Sanitizer pass over the shm store (reference practice: C++ components
# run under TSAN/ASAN in CI, SURVEY §5.2).  Builds the real store code
# single-TU with the multi-threaded stress harness and runs it under
# ThreadSanitizer and AddressSanitizer+UBSan.
set -euo pipefail
cd "$(dirname "$0")"
out="${TMPDIR:-/tmp}/rts_sanitizers"
mkdir -p "$out"
echo "== TSAN =="
g++ -O1 -g -fsanitize=thread -pthread shmstore_stress.cc -o "$out/stress_tsan"
"$out/stress_tsan"
echo "== ASAN+UBSAN =="
g++ -O1 -g -fsanitize=address,undefined -pthread shmstore_stress.cc -o "$out/stress_asan"
"$out/stress_asan"
echo "sanitizers clean"
