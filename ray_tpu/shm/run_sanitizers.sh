#!/bin/bash
# Sanitizer pass over the shm store (reference practice: C++ components
# run under TSAN/ASAN/UBSAN in CI, SURVEY §5.2).  Builds the real store
# code single-TU with the multi-threaded stress harness and runs it
# under ThreadSanitizer, AddressSanitizer(+UBSan), and a standalone
# UndefinedBehaviorSanitizer pass — pure UBSAN instruments without
# ASAN's shadow-memory remapping, so it additionally runs the shm
# layout at production addresses and traps on ANY report
# (-fno-sanitize-recover) instead of printing and continuing.
set -euo pipefail
cd "$(dirname "$0")"
out="${TMPDIR:-/tmp}/rts_sanitizers"
mkdir -p "$out"
echo "== TSAN =="
g++ -O1 -g -fsanitize=thread -pthread shmstore_stress.cc -o "$out/stress_tsan" -lrt
"$out/stress_tsan"
echo "== ASAN+UBSAN =="
g++ -O1 -g -fsanitize=address,undefined -pthread shmstore_stress.cc -o "$out/stress_asan" -lrt
"$out/stress_asan"
echo "== UBSAN =="
g++ -O1 -g -fsanitize=undefined -fno-sanitize-recover=all -pthread shmstore_stress.cc -o "$out/stress_ubsan" -lrt
"$out/stress_ubsan"
echo "sanitizers clean"
