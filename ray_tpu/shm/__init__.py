"""ctypes binding for the C++ shared-memory object store.

The binding seam mirrors the reference's choice of a thin native binding
under the Python API (`python/ray/_raylet.pyx` over the C++ core), using
ctypes + an extern-C surface instead of Cython.  Zero-copy reads: Python
mmaps the same ``/dev/shm`` segment and returns memoryviews at the
offsets the C side hands back.
"""

from __future__ import annotations

import ctypes
import logging
import mmap
import os
import re
import subprocess
import threading

from ray_tpu.util import sanitizer as _sanitizer

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "shmstore.cc")
_LIB = os.path.join(_HERE, "libshmstore.so")

OK = 0
EXISTS = -1
NOT_FOUND = -2
OOM = -3
TIMEOUT = -4
BAD_STATE = -5

# kind sealed on a slot whose payload overflowed the slot capacity
# after acquire (endpoints disagreeing on ring geometry): the slot is
# published zero-length under this marker so the ring is never left
# acquired-but-unsealed, and the READER surfaces a typed error instead
# of decoding garbage (ray_tpu/dag/channel.py handles it)
KIND_OVERFLOW_MARKER = 0x7FFFFFFF

_build_lock = threading.Lock()


def _ensure_built() -> str:
    with _build_lock:
        if (not os.path.exists(_LIB)) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
            tmp = _LIB + f".tmp.{os.getpid()}"
            # one-time native build at first touch, cached on mtime;
            # any caller (sync or async) accepts the startup hit
            subprocess.run(  # rtlint: disable=RT009
                ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, _SRC, "-lpthread", "-lrt"],
                check=True,
                capture_output=True,
            )
            os.replace(tmp, _LIB)
    return _LIB


_lib = None
_lib_lock = threading.Lock()


def _load():
    global _lib
    with _lib_lock:
        if _lib is None:
            lib = ctypes.CDLL(_ensure_built())
            u64 = ctypes.c_uint64
            p = ctypes.c_void_p
            lib.rts_create_store.restype = p
            lib.rts_create_store.argtypes = [ctypes.c_char_p, u64, u64]
            lib.rts_open_store.restype = p
            lib.rts_open_store.argtypes = [ctypes.c_char_p]
            lib.rts_close.argtypes = [p]
            lib.rts_unlink.argtypes = [ctypes.c_char_p]
            lib.rts_create.argtypes = [p, ctypes.c_char_p, u64, ctypes.POINTER(u64)]
            lib.rts_create_ex.argtypes = [p, ctypes.c_char_p, u64,
                                          ctypes.POINTER(u64), ctypes.c_int]
            lib.rts_seal.argtypes = [p, ctypes.c_char_p]
            lib.rts_get.argtypes = [p, ctypes.c_char_p, ctypes.c_int64,
                                    ctypes.POINTER(u64), ctypes.POINTER(u64)]
            lib.rts_release.argtypes = [p, ctypes.c_char_p]
            lib.rts_delete.argtypes = [p, ctypes.c_char_p]
            lib.rts_contains.argtypes = [p, ctypes.c_char_p]
            lib.rts_reap_creator.argtypes = [p, u64]
            lib.rts_spill_candidates.restype = u64
            lib.rts_spill_candidates.argtypes = [p, ctypes.c_char_p, u64]
            u32p = ctypes.POINTER(ctypes.c_uint32)
            u64p = ctypes.POINTER(u64)
            lib.rts_chan_create.argtypes = [p, ctypes.c_char_p, u64, u64]
            lib.rts_chan_write_acquire.argtypes = [
                p, ctypes.c_char_p, ctypes.c_int64, u64p, u64p]
            lib.rts_chan_write_seal.argtypes = [
                p, ctypes.c_char_p, u64, ctypes.c_uint32]
            lib.rts_chan_read_acquire.argtypes = [
                p, ctypes.c_char_p, ctypes.c_int64, u64p, u64p, u32p]
            lib.rts_chan_read_release.argtypes = [p, ctypes.c_char_p]
            lib.rts_chan_close.argtypes = [p, ctypes.c_char_p]
            for fn in ("rts_used", "rts_capacity", "rts_count", "rts_evictions"):
                getattr(lib, fn).restype = u64
                getattr(lib, fn).argtypes = [p]
            _lib = lib
    return _lib


# Store names carry their owning daemon pid as a ".<pid>" suffix
# (noded appends it at creation) so a later boot can tell an orphan —
# a segment whose owner was SIGKILLed before it could unlink — from a
# live neighbor's store on the same host.
_OWNER_SUFFIX_RE = re.compile(r"\.(\d+)$")


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        # EPERM and friends: the pid exists but belongs to someone else
        return True
    return True


def sweep_stale_segments(prefix: str = "rt_",
                         shm_dir: str = "/dev/shm") -> list:
    """Reap `/dev/shm/<prefix>*` segments whose owning session pid is
    dead (VERDICT Weak #6: a SIGKILLed daemon never unlinks its store,
    and leaked segments eat the shared host's shm budget forever).

    Only segments carrying an owner-pid suffix are judged; anything
    else (foreign naming schemes, pre-suffix legacy segments) is left
    alone.  Returns the names removed."""
    removed = []
    try:
        entries = os.listdir(shm_dir)
    except OSError as e:
        logger.debug("cannot list %s: %s", shm_dir, e)
        return removed
    for name in entries:
        if not name.startswith(prefix):
            continue
        m = _OWNER_SUFFIX_RE.search(name)
        if not m:
            continue
        pid = int(m.group(1))
        if pid <= 0 or _pid_alive(pid):
            continue
        try:
            os.unlink(os.path.join(shm_dir, name))
        except OSError as e:
            logger.debug("could not reap stale segment %s: %s", name, e)
            continue
        removed.append(name)
    if removed:
        logger.info("reaped %d stale shm segment(s) from dead sessions: %s",
                    len(removed), ", ".join(sorted(removed)))
    return removed


class ShmStoreError(Exception):
    pass


class ObjectExistsError(ShmStoreError):
    pass


class ObjectNotFoundError(ShmStoreError):
    pass


class StoreFullError(ShmStoreError):
    pass


class ChannelClosedError(ShmStoreError):
    pass


def _check(rc: int, what: str):
    if rc == OK:
        return
    if rc == EXISTS:
        raise ObjectExistsError(what)
    if rc == NOT_FOUND:
        raise ObjectNotFoundError(what)
    if rc == OOM:
        raise StoreFullError(what)
    if rc == TIMEOUT:
        raise TimeoutError(what)
    raise ShmStoreError(f"{what}: rc={rc}")


def _pad_id(object_id: bytes) -> bytes:
    if len(object_id) != 18:
        raise ValueError(f"object id must be 18 bytes, got {len(object_id)}")
    return object_id


class ShmStore:
    """One node-local store segment; open once per process."""

    def __init__(self, name: str, capacity: int = 0, create: bool = False,
                 table_cap: int = 0):
        self.name = name
        lib = _load()
        if create:
            if capacity <= 0:
                raise ValueError("capacity must be > 0 when creating a store")
            self._h = lib.rts_create_store(name.encode(), capacity, table_cap)
        else:
            self._h = lib.rts_open_store(name.encode())
        if not self._h:
            raise ShmStoreError(
                f"could not {'create' if create else 'open'} store {name!r}"
            )
        # Python-side zero-copy view of the same segment.
        fd = os.open(f"/dev/shm/{name.lstrip('/')}", os.O_RDWR)
        try:
            size = os.fstat(fd).st_size
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self._view = memoryview(self._mm)
        self._closed = False

    # -- lifecycle -----------------------------------------------------
    def close(self):
        if not self._closed:
            self._closed = True
            try:
                self._view.release()
                self._mm.close()
            except BufferError:
                # User-held memoryviews keep the mapping alive; the OS
                # reclaims it at process exit.
                pass
            _load().rts_close(self._h)

    @staticmethod
    def unlink(name: str):
        _load().rts_unlink(name.encode())

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- object ops ----------------------------------------------------
    def create(self, object_id: bytes, size: int,
               allow_evict: bool = True) -> memoryview:
        """Allocate a writable buffer; caller must seal() when done.
        allow_evict=False never destroys sealed primaries for room — the
        runtime uses it so pressure is resolved by disk spilling
        (preserving data) instead of destructive LRU eviction."""
        off = ctypes.c_uint64()
        rc = _load().rts_create_ex(self._h, _pad_id(object_id), size,
                                   ctypes.byref(off), 1 if allow_evict else 0)
        _check(rc, f"create {object_id.hex()}")
        _sanitizer.note_acquire(
            "store-create", object_id.hex(),
            f"object {object_id.hex()} ({size}B) created but never "
            "sealed/aborted — pins arena and wedges readers",
        )
        return self._view[off.value : off.value + size]

    def seal(self, object_id: bytes):
        _check(_load().rts_seal(self._h, _pad_id(object_id)), f"seal {object_id.hex()}")
        _sanitizer.note_release("store-create", object_id.hex())

    def put(self, object_id: bytes, data, allow_evict: bool = True) -> None:
        """create + copy + seal in one call."""
        data = memoryview(data).cast("B")
        buf = self.create(object_id, data.nbytes, allow_evict=allow_evict)
        buf[:] = data
        self.seal(object_id)

    def get(self, object_id: bytes, timeout_ms: int = 0) -> memoryview:
        """Pin and return a read view.  timeout_ms: 0 = non-blocking,
        <0 = wait forever."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = _load().rts_get(self._h, _pad_id(object_id), timeout_ms,
                             ctypes.byref(off), ctypes.byref(size))
        _check(rc, f"get {object_id.hex()}")
        return self._view[off.value : off.value + size.value]

    def release(self, object_id: bytes):
        _load().rts_release(self._h, _pad_id(object_id))

    def delete(self, object_id: bytes) -> bool:
        rc = _load().rts_delete(self._h, _pad_id(object_id))
        if rc == OK:
            _sanitizer.note_release("store-create", object_id.hex())
        return rc == OK

    def abort(self, object_id: bytes) -> bool:
        """Discard an UNSEALED create, releasing its allocation.

        A created-but-unsealed object holds its creator pin, so a bare
        `delete` refuses with BAD_STATE and the partial allocation
        leaks until a creator-death reap that may never come (the
        creator is alive, its transfer/restore just failed).  This
        drops the creator pin first, then deletes — the abort half of
        the create/seal pair."""
        lib = _load()
        oid = _pad_id(object_id)
        lib.rts_release(self._h, oid)
        _sanitizer.note_release("store-create", object_id.hex())
        return lib.rts_delete(self._h, oid) == OK

    def contains(self, object_id: bytes) -> bool:
        return bool(_load().rts_contains(self._h, _pad_id(object_id)))

    def reap_creator(self, pid: int) -> int:
        """Drop unsealed objects created by a dead process."""
        return _load().rts_reap_creator(self._h, pid)

    def spill_candidates(self, max_ids: int = 64) -> list:
        """LRU-ordered ids of sealed, unpinned objects (the spill
        manager's shopping list)."""
        buf = ctypes.create_string_buffer(18 * max_ids)
        n = _load().rts_spill_candidates(self._h, buf, max_ids)
        raw = buf.raw
        return [raw[i * 18:(i + 1) * 18] for i in range(n)]

    # -- stats ---------------------------------------------------------
    @property
    def used(self) -> int:
        return _load().rts_used(self._h)

    @property
    def capacity(self) -> int:
        return _load().rts_capacity(self._h)

    @property
    def count(self) -> int:
        return _load().rts_count(self._h)

    @property
    def evictions(self) -> int:
        return _load().rts_evictions(self._h)

    # -- mutable channels ----------------------------------------------
    def chan_create(self, chan_id: bytes, nslots: int = 8,
                    slot_size: int = 128 * 1024) -> bool:
        """Create (or open, if the peer already created it) a mutable
        SPSC channel — the native substrate for compiled-DAG channels
        (reference: `experimental_mutable_object_manager.h:48`).
        Returns True if this call created it."""
        rc = _load().rts_chan_create(
            self._h, _pad_id(chan_id), nslots, slot_size
        )
        if rc == OK:
            return True
        if rc == EXISTS:
            return False
        _check(rc, f"chan_create {chan_id.hex()}")
        return False

    def chan_write(self, chan_id: bytes, payload, kind: int = 0,
                   timeout_ms: int = -1):
        """Acquire a slot (blocking while the ring is full), copy the
        payload in, publish.  Zero allocation per message."""
        lib = _load()
        cid = _pad_id(chan_id)
        off = ctypes.c_uint64()
        cap = ctypes.c_uint64()
        rc = lib.rts_chan_write_acquire(
            self._h, cid, timeout_ms, ctypes.byref(off), ctypes.byref(cap)
        )
        if rc == BAD_STATE:
            raise ChannelClosedError(chan_id.hex())
        _check(rc, f"chan_write_acquire {chan_id.hex()}")
        _sanitizer.note_acquire(
            "ring-slot", chan_id.hex(),
            f"channel {chan_id.hex()} slot acquired but never sealed "
            "— ring wedged for every later writer",
        )
        data = payload if isinstance(payload, (bytes, bytearray, memoryview)) \
            else bytes(payload)
        n = len(data)
        if n > cap.value:
            # same invariant as chan_write_chunks: never leave the slot
            # acquired-but-unsealed (that wedges the ring for every
            # later writer) — publish the typed overflow marker instead
            lib.rts_chan_write_seal(self._h, cid, 0, KIND_OVERFLOW_MARKER)
            _sanitizer.note_release("ring-slot", chan_id.hex())
            raise ValueError(
                f"payload {n}B exceeds channel slot size {cap.value}B"
            )
        self._view[off.value:off.value + n] = bytes(data)
        _check(
            lib.rts_chan_write_seal(self._h, cid, n, kind),
            f"chan_write_seal {chan_id.hex()}",
        )
        _sanitizer.note_release("ring-slot", chan_id.hex())

    def chan_write_chunks(self, chan_id: bytes, chunks, kind: int = 0,
                          timeout_ms: int = -1):
        """Acquire a slot and write a scatter list of buffers at their
        running offsets — the tensor fast path publishes a header plus
        several raw array buffers in ONE slot publication without
        assembling an intermediate contiguous copy.

        Overflow invariant: the slot capacity is only known after the
        acquire, so an oversized payload (endpoints disagreeing on ring
        geometry) is sealed as a zero-length KIND_OVERFLOW_MARKER —
        never left acquired-but-unsealed, which would wedge the ring
        for every later writer."""
        lib = _load()
        cid = _pad_id(chan_id)
        views = [memoryview(c).cast("B") for c in chunks]
        total = sum(v.nbytes for v in views)
        off = ctypes.c_uint64()
        cap = ctypes.c_uint64()
        rc = lib.rts_chan_write_acquire(
            self._h, cid, timeout_ms, ctypes.byref(off), ctypes.byref(cap)
        )
        if rc == BAD_STATE:
            raise ChannelClosedError(chan_id.hex())
        _check(rc, f"chan_write_acquire {chan_id.hex()}")
        _sanitizer.note_acquire(
            "ring-slot", chan_id.hex(),
            f"channel {chan_id.hex()} slot acquired but never sealed "
            "— ring wedged for every later writer",
        )
        if total > cap.value:
            # reachable only when endpoints disagree on ring geometry
            # (the creator's slot size won): seal a zero-length marker
            # rather than leave the slot acquired (which would wedge
            # the ring); the reader raises typed on the marker
            lib.rts_chan_write_seal(self._h, cid, 0, KIND_OVERFLOW_MARKER)
            _sanitizer.note_release("ring-slot", chan_id.hex())
            raise ValueError(
                f"payload {total}B exceeds channel slot size {cap.value}B"
            )
        pos = off.value
        for v in views:
            self._view[pos:pos + v.nbytes] = v
            pos += v.nbytes
        _check(
            lib.rts_chan_write_seal(self._h, cid, total, kind),
            f"chan_write_seal {chan_id.hex()}",
        )
        _sanitizer.note_release("ring-slot", chan_id.hex())

    def chan_read(self, chan_id: bytes, timeout_ms: int = -1):
        """Blocking read: returns (kind, bytes) of the next message and
        releases the slot back to the writer."""
        lib = _load()
        cid = _pad_id(chan_id)
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        kind = ctypes.c_uint32()
        rc = lib.rts_chan_read_acquire(
            self._h, cid, timeout_ms, ctypes.byref(off), ctypes.byref(size),
            ctypes.byref(kind),
        )
        if rc == BAD_STATE:
            raise ChannelClosedError(chan_id.hex())
        _check(rc, f"chan_read_acquire {chan_id.hex()}")
        data = bytes(self._view[off.value:off.value + size.value])
        _check(
            lib.rts_chan_read_release(self._h, cid),
            f"chan_read_release {chan_id.hex()}",
        )
        return kind.value, data

    def chan_close(self, chan_id: bytes):
        """Mark closed: readers drain then see ChannelClosedError;
        writers fail immediately."""
        rc = _load().rts_chan_close(self._h, _pad_id(chan_id))
        if rc not in (OK, NOT_FOUND):
            _check(rc, f"chan_close {chan_id.hex()}")

    def chan_delete(self, chan_id: bytes):
        self.release(chan_id)  # drop the create-time pin
        self.delete(chan_id)
