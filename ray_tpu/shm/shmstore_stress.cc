// Multi-threaded stress harness for the shm store, built to run under
// TSAN/ASAN (reference practice: the C++ store is CI-tested under
// sanitizers, SURVEY §5.2 / plasma's gtest+sanitizer runs).
//
// Threads hammer the full object lifecycle (create/seal/get/release/
// delete with eviction pressure) plus one SPSC channel pair, all
// against a single segment.  The process-shared robust mutexes are
// ordinary pthread mutexes within one process, so TSAN sees every
// lock/unlock edge the daemon/worker processes would take.
//
// Build+run (see run_sanitizers.sh):
//   g++ -O1 -g -fsanitize=thread  -pthread shmstore_stress.cc -o t && ./t
//   g++ -O1 -g -fsanitize=address -pthread shmstore_stress.cc -o a && ./a

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include "shmstore.cc"  // single-TU build: sanitize the real code

namespace {

// ids use the store's padded width (shmstore.cc kIdLen = 24)
constexpr int kThreads = 4;
constexpr int kOpsPerThread = 3000;

void make_id(uint8_t* id, int thread_id, int n) {  // 24-byte padded id
  std::memset(id, 0, 24);
  id[0] = (uint8_t)thread_id;
  std::memcpy(id + 1, &n, sizeof(n));
}

std::atomic<int> failures{0};

void object_worker(void* h, int tid) {
  uint8_t id[24];
  for (int i = 0; i < kOpsPerThread; ++i) {
    make_id(id, tid, i);
    uint64_t off = 0;
    uint64_t size = 256 + (i % 7) * 1024;
    int rc = rts_create_ex(h, id, size, &off, /*allow_evict=*/1);
    if (rc != RTS_OK) continue;  // store full under pressure: fine
    rts_seal(h, id);
    uint64_t goff = 0, gsize = 0;
    if (rts_get(h, id, /*timeout_ms=*/0, &goff, &gsize) == RTS_OK) {
      if (gsize != size) failures.fetch_add(1);
      rts_release(h, id);
    }
    if (i % 3 == 0) rts_delete(h, id);
    if (i % 97 == 0) {
      uint8_t ids[32 * 24];
      rts_spill_candidates(h, ids, 32);
    }
  }
}

void chan_writer(void* h, const uint8_t* cid, int messages) {
  for (int i = 0; i < messages; ++i) {
    uint64_t off = 0, cap = 0;
    if (rts_chan_write_acquire(h, cid, 5000, &off, &cap) != RTS_OK) {
      failures.fetch_add(1);
      return;
    }
    std::memcpy((char*)((Handle*)h)->base + off, &i, sizeof(i));
    rts_chan_write_seal(h, cid, sizeof(i), /*kind=*/0);
  }
}

void chan_reader(void* h, const uint8_t* cid, int messages) {
  for (int i = 0; i < messages; ++i) {
    uint64_t off = 0, size = 0;
    uint32_t kind = 0;
    if (rts_chan_read_acquire(h, cid, 5000, &off, &size, &kind) != RTS_OK) {
      failures.fetch_add(1);
      return;
    }
    int got = -1;
    std::memcpy(&got, (char*)((Handle*)h)->base + off, sizeof(got));
    if (got != i) failures.fetch_add(1);
    rts_chan_read_release(h, cid);
  }
}

}  // namespace

int main() {
  const char* name = "/rts_sanitizer_stress";
  rts_unlink(name);
  void* h = rts_create_store(name, /*capacity=*/8 << 20, /*table_cap=*/4096);
  if (!h) {
    std::fprintf(stderr, "create_store failed\n");
    return 2;
  }

  uint8_t cid[24];
  std::memset(cid, 0xCC, 24);
  if (rts_chan_create(h, cid, /*nslots=*/8, /*slot_size=*/4096) != RTS_OK) {
    std::fprintf(stderr, "chan_create failed\n");
    return 2;
  }

  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; ++t)
    ts.emplace_back(object_worker, h, t);
  constexpr int kMsgs = 5000;
  ts.emplace_back(chan_writer, h, cid, kMsgs);
  ts.emplace_back(chan_reader, h, cid, kMsgs);
  for (auto& t : ts) t.join();

  rts_close(h);
  rts_unlink(name);
  if (failures.load() != 0) {
    std::fprintf(stderr, "stress failures: %d\n", failures.load());
    return 1;
  }
  std::printf("shmstore stress OK (%d threads x %d ops + %d chan msgs)\n",
              kThreads, kOpsPerThread, kMsgs);
  return 0;
}
