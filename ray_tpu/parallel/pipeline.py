"""Pipeline parallelism over the `pp` mesh axis.

Absent as a first-class strategy in the reference (SURVEY §2.5: PP
"expressible via aDAG multi-actor pipelines" only).  Here it is a
compiled-program strategy: stage parameters are sharded over `pp`
(leading stage dim), and a GPipe microbatch schedule runs inside
`shard_map` — each step every device computes its resident stage and
hands its activation to the next stage with `lax.ppermute` (ICI
neighbor exchange).  Compute on microbatch m overlaps the transfer of
microbatch m-1; the bubble is the standard (S-1)/(M+S-1) fraction.
The whole schedule is one `lax.scan`, so XLA compiles a single step
body regardless of microbatch count, and `jax.grad` differentiates
straight through it (backward replays the ring in reverse).

For cross-host pipelines where stages cannot share a jit program, the
actor-level alternative is `ray_tpu.dag` compiled graphs (the
reference's aDAG pattern) — same schedule, channels instead of
ppermute.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map


def stage_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for stage-stacked params (leading dim = num stages)."""
    return NamedSharding(mesh, P("pp"))


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    mesh: Mesh,
    num_microbatches: int,
):
    """Run x [B, ...] through S pipeline stages.

    stage_params: pytree whose leaves have leading dim S (sharded over
    `pp`); stage_fn(params_slice, microbatch) -> microbatch-shaped
    output (stages must preserve the activation shape, the usual
    transformer-block contract).

    B must divide into num_microbatches equal microbatches.
    """
    S = mesh.shape["pp"]
    for leaf in jax.tree.leaves(stage_params):
        if leaf.shape[0] != S:
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} must equal the "
                f"mesh's pp size {S} — a mismatch would silently drop "
                "stages after sharding"
            )
    B = x.shape[0]
    M = num_microbatches
    assert B % M == 0, f"num_microbatches {M} must divide batch {B}"
    mb = B // M
    xs = x.reshape(M, mb, *x.shape[1:])

    def body(params, xs_local):
        # params: this device's stage slice, leading dim 1
        params_local = jax.tree.map(lambda p: p[0], params)
        idx = lax.axis_index("pp")
        T = M + S - 1  # schedule length incl. pipeline bubble
        fwd_perm = [(i, i + 1) for i in range(S - 1)]

        def step(carry, t):
            recv, outs = carry
            # stage 0 consumes microbatch t while t < M; later stages
            # consume what arrived from the previous stage
            feed_idx = jnp.clip(t, 0, M - 1)
            x_in = jnp.where(idx == 0, xs_local[feed_idx], recv)
            y = stage_fn(params_local, x_in)
            # last stage banks its result for microbatch t - (S - 1)
            out_slot = jnp.clip(t - (S - 1), 0, M - 1)
            take = jnp.logical_and(idx == S - 1, t >= S - 1)
            outs = lax.cond(
                take,
                lambda o: o.at[out_slot].set(y),
                lambda o: o,
                outs,
            )
            recv = lax.ppermute(y, "pp", fwd_perm)
            return (recv, outs), None

        outs0 = jnp.zeros_like(xs_local)
        recv0 = jnp.zeros_like(xs_local[0])
        (recv, outs), _ = lax.scan(step, (recv0, outs0), jnp.arange(T))
        # only the last stage holds real outputs; a masked psum
        # broadcasts them so every device returns the coherent batch
        contrib = jnp.where(idx == S - 1, outs, jnp.zeros_like(outs))
        return lax.psum(contrib, "pp")

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P("pp"), P()),
        out_specs=P(),
        check_rep=False,
    )
    out = fn(stage_params, xs)
    return out.reshape(B, *x.shape[1:])
