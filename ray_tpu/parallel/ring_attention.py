"""Ring attention: sequence/context parallelism over the `sp` mesh axis.

Absent from the reference (SURVEY §5.7 verified no ring/Ulysses
implementation exists there); built natively here as the long-context
strategy.  Design: q/k/v are sharded over the sequence axis; each device
keeps its Q shard resident and passes its K/V shard around the ring with
`lax.ppermute` (which XLA lowers to ICI neighbor exchanges), folding
each visiting block into a running flash-style online softmax.  Compute
on block i overlaps with the transfer of block i+1 (XLA schedules the
ppermute concurrently with the einsums since there is no data
dependency).

Also provides Ulysses-style all-to-all attention: scatter heads /
gather sequence via `lax.all_to_all`, run full-sequence attention per
head group, invert.  Ring scales to sequence lengths that don't fit a
chip; Ulysses is cheaper at moderate lengths when heads >= sp.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

_NEG_INF = -1e30


def select_attention(kind: str, q, k, v, mesh=None, causal: bool = True):
    """One dispatch point for the attention backends (dense | flash |
    ring | ulysses) shared by all model families."""
    if kind == "flash":
        from ray_tpu.ops import flash_attention

        return flash_attention(q, k, v, causal)
    if kind == "ring" and mesh is not None:
        return ring_attention(q, k, v, mesh, causal=causal)
    if kind == "ulysses" and mesh is not None:
        return ulysses_attention(q, k, v, mesh, causal=causal)
    return plain_attention(q, k, v, causal=causal)


def _block_attn(q, k, v, bias, scale):
    """One q-block x kv-block attention with streaming-softmax stats.

    Returns (unnormalized_out, row_max, row_sumexp)."""
    # q: [B, Tq, H, D], k/v: [B, Tk, H, D]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1)  # [B, H, Tq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)  # [B, H, Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return o, m, l


def _ring_attention_local(q, k, v, axis_name: str, causal: bool, scale: float):
    """Per-device body under shard_map; sequence dim is the local shard."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]

    def make_bias(kv_idx):
        if not causal:
            return None
        # global positions: rows my_idx*Tq + iq, cols kv_idx*Tk + ik
        rows = my_idx * Tq + jnp.arange(Tq)[:, None]
        cols = kv_idx * Tk + jnp.arange(Tk)[None, :]
        return jnp.where(rows >= cols, 0.0, _NEG_INF)[None, None, :, :]

    def step(carry, _):
        o_acc, m_acc, l_acc, k_cur, v_cur, step_i = carry
        kv_idx = (my_idx - step_i) % axis_size
        o_b, m_b, l_b = _block_attn(q, k_cur, v_cur, make_bias(kv_idx), scale)
        # online softmax merge (flash-attention style)
        m_new = jnp.maximum(m_acc, m_b)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m_b - m_new)
        l_new = l_acc * alpha + l_b * beta
        o_new = (
            o_acc * alpha.transpose(0, 2, 1)[..., None]
            + o_b * beta.transpose(0, 2, 1)[..., None]
        )
        # rotate k/v to the next ring neighbor (ICI exchange)
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt, step_i + 1), None

    o0 = jnp.zeros_like(q)
    m0 = jnp.full((B, H, Tq), _NEG_INF, dtype=q.dtype)
    l0 = jnp.zeros((B, H, Tq), dtype=q.dtype)
    (o, m, l, _, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v, jnp.int32(0)), None, length=axis_size
    )
    l = jnp.maximum(l, 1e-20)
    return o / l.transpose(0, 2, 1)[..., None]


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Attention over sequence-sharded q/k/v of shape [B, T, H, D].

    T is the GLOBAL sequence length; inputs may be unsharded (the
    shard_map in/out specs place them).  Batch stays sharded over
    (dp, fsdp), heads over tp, sequence over `axis_name`.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(("dp", "fsdp"), axis_name, "tp", None)
    fn = shard_map(
        functools.partial(
            _ring_attention_local, axis_name=axis_name, causal=causal, scale=scale
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )
    return fn(q, k, v)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Ulysses/DeepSpeed-style SP: all_to_all so each device holds the
    FULL sequence for a subset of heads, then dense attention, then the
    inverse all_to_all.  Requires H % sp == 0."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(("dp", "fsdp"), axis_name, "tp", None)

    def local(q, k, v):
        # local shapes: [b, t_local, h, d]; scatter heads, gather seq
        def a2a(x):
            return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

        def a2a_inv(x):
            return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

        qg, kg, vg = a2a(q), a2a(k), a2a(v)  # [b, T, h/sp, d]
        T = qg.shape[1]
        bias = None
        if causal:
            rows = jnp.arange(T)[:, None]
            cols = jnp.arange(T)[None, :]
            bias = jnp.where(rows >= cols, 0.0, _NEG_INF)[None, None, :, :]
        o, m, l = _block_attn(qg, kg, vg, bias, scale)
        o = o / jnp.maximum(l, 1e-20).transpose(0, 2, 1)[..., None]
        return a2a_inv(o)

    fn = shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False,
    )
    return fn(q, k, v)


def plain_attention(q, k, v, *, causal=True, scale=None):
    """Reference (unsharded) attention used in tests and as the
    single-device path."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        T, S = s.shape[-2], s.shape[-1]
        mask = jnp.arange(T)[:, None] >= jnp.arange(S)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
