"""First-class 1F1B pipeline parallelism across stage actors.

The reference never made pipeline parallelism first-class — SURVEY §2.5
notes PP is only "expressible via aDAG".  This module makes it one:
stage actors (each on its own worker / slice sub-mesh) are connected by
compiled-DAG tensor channels (`dag/channel.py` KIND_TENSOR — raw
activation bytes, no pickle), and a 1F1B microbatch schedule is
compiled into each stage's resident exec-loop plan:

- warmup: stage s runs min(S-1-s, M) forwards before its first
  backward (filling the pipe);
- steady: strict 1F1B alternation — one forward, one backward — which
  caps live activations at S-s instead of GPipe's M;
- cooldown: the remaining backwards drain the pipe.

Forward activations flow over per-edge channels ring-buffered with 2
slots (double buffering: microbatch m's transfer overlaps microbatch
m+1's compute); backward activation-gradients flow over reverse
channels the same way.  Each stage accumulates its parameter grads
across microbatches locally; data-parallel replicas of a stage close
the accumulation with the existing collectives (`parallel/collectives`)
exactly like any other grad.

The in-program, single-jit-program alternative (same math, ICI
`ppermute` instead of channels) is `parallel/pipeline.py`; the parity
tests gate this module's loss/grads against it and against serial
application.

Bubble accounting matches the standard model the in-program schedule
tests use: with equal unit F and B costs the schedule spans
``2*(M + S - 1)`` unit slots, i.e. a bubble fraction of
``(S-1)/(M+S-1)``.
"""

from __future__ import annotations

import logging
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.dag.channel import (
    Channel,
    ChannelClosed,
    ChannelPollTimeout,
)

logger = logging.getLogger(__name__)


# -- schedule ----------------------------------------------------------
def one_f1b_schedule(stage: int, num_stages: int, num_microbatches: int
                     ) -> List[Tuple[str, int]]:
    """The op sequence stage `stage` executes per batch: ("F", mb) /
    ("B", mb) in warmup -> steady(1F1B) -> cooldown order."""
    S, M, s = num_stages, num_microbatches, stage
    warmup = min(S - 1 - s, M)
    ops: List[Tuple[str, int]] = [("F", m) for m in range(warmup)]
    f, b = warmup, 0
    while f < M:
        ops.append(("F", f))
        f += 1
        ops.append(("B", b))
        b += 1
    while b < M:
        ops.append(("B", b))
        b += 1
    return ops


def schedule_phases(stage: int, num_stages: int, num_microbatches: int
                    ) -> Dict[str, int]:
    """Warmup/steady/cooldown op counts for one stage (introspection
    for tests and docs)."""
    warmup = min(num_stages - 1 - stage, num_microbatches)
    steady = 2 * (num_microbatches - warmup)
    cooldown = 2 * num_microbatches - warmup - steady
    return {"warmup": warmup, "steady": steady, "cooldown": cooldown}


def bubble_fraction(num_stages: int, num_microbatches: int) -> float:
    """Idle fraction of the schedule: (S-1)/(M+S-1) — identical to the
    in-program GPipe schedule's model (`parallel/pipeline.py`)."""
    S, M = num_stages, num_microbatches
    return (S - 1) / (M + S - 1)


def schedule_makespan_units(num_stages: int, num_microbatches: int) -> int:
    """Simulated makespan of the 1F1B schedule in unit slots (F and B
    each cost 1, transfers free): dependency-driven event simulation
    over every stage's op list.  With equal F/B this is
    ``2*(M + S - 1)``, matching the bubble model above."""
    S, M = num_stages, num_microbatches
    ops = {s: one_f1b_schedule(s, S, M) for s in range(S)}
    pos = {s: 0 for s in range(S)}
    free = {s: 0 for s in range(S)}  # stage available time
    fin_f: Dict[Tuple[int, int], int] = {}
    fin_b: Dict[Tuple[int, int], int] = {}
    remaining = sum(len(v) for v in ops.values())
    while remaining:
        progressed = False
        for s in range(S):
            if pos[s] >= len(ops[s]):
                continue
            kind, m = ops[s][pos[s]]
            if kind == "F":
                dep = fin_f.get((s - 1, m), 0) if s > 0 else 0
                if s > 0 and (s - 1, m) not in fin_f:
                    continue
                start = max(free[s], dep)
                fin_f[(s, m)] = start + 1
            else:
                if s < S - 1 and (s + 1, m) not in fin_b:
                    continue
                dep = fin_b.get((s + 1, m), 0) if s < S - 1 else (
                    fin_f[(s, m)]
                )
                start = max(free[s], dep, fin_f[(s, m)])
                fin_b[(s, m)] = start + 1
            free[s] = start + 1
            pos[s] += 1
            remaining -= 1
            progressed = True
        if not progressed:
            raise RuntimeError("1F1B schedule deadlocked (model bug)")
    return max(free.values())


# -- stage actor -------------------------------------------------------
class _PipelineStage:
    """One pipeline stage: holds its parameter shard and runs the
    compiled 1F1B plan as a resident loop (launched like a compiled-DAG
    exec loop: one long-lived actor task, torn down by channel close).
    """

    def __init__(self, stage_fn: Callable, params: Any, stage: int,
                 num_stages: int, loss_fn: Optional[Callable] = None):
        self._stage_fn = stage_fn
        self._params = params
        self._s = stage
        self._S = num_stages
        self._loss_fn = loss_fn

    def ping(self) -> bool:
        return True

    def run(self, plan: Dict) -> int:
        """Resident 1F1B loop.  Per batch execution: run the op
        schedule, then publish this stage's accumulated grads (and, on
        the last stage, the mean microbatch loss) to the driver.
        Returns the number of completed batch executions at teardown.
        """
        import jax
        import numpy as np

        s, S, M = self._s, self._S, plan["num_microbatches"]
        rs = plan.get("ring_slots", 2)
        chans: Dict[str, Channel] = {}

        def chan(key) -> Optional[Channel]:
            ref = plan.get(key)
            if ref is None:
                return None
            c = chans.get(key)
            if c is None:
                if key == "in_chan":
                    # MUST match the driver's sizing: whichever endpoint
                    # opens the ring first creates it, and creator wins
                    slots = plan.get("in_ring_slots")
                elif key == "result":
                    slots = None
                else:
                    slots = rs
                c = chans[key] = Channel(ref[0], ref[1], ring_slots=slots)
            return c

        in_chan = chan("in_chan")
        fwd_in, fwd_out = chan("fwd_in"), chan("fwd_out")
        bwd_in, bwd_out = chan("bwd_in"), chan("bwd_out")
        result = chan("result")
        ops = one_f1b_schedule(s, S, M)
        loss_grad = (jax.value_and_grad(self._loss_fn)
                     if self._loss_fn is not None else None)
        inv_m = 1.0 / float(M)
        executions = 0
        try:
            while True:
                vjps: Dict[int, Any] = {}
                pending_gy: Dict[int, Any] = {}
                grads = None
                loss_sum = 0.0
                for kind, m in ops:
                    if kind == "F":
                        src = in_chan if s == 0 else fwd_in
                        x = src.read()
                        y, vjp = jax.vjp(self._stage_fn, self._params, x)
                        vjps[m] = vjp
                        if s == S - 1:
                            # last stage closes the loss: grad wrt its
                            # own output, scaled by 1/M so the summed
                            # accumulation equals the full-batch mean
                            loss_m, gy = loss_grad(y)
                            loss_sum += float(loss_m)
                            pending_gy[m] = jax.tree.map(
                                lambda g: g * inv_m, gy
                            )
                        else:
                            fwd_out.write(y)
                    else:
                        gy = (pending_gy.pop(m) if s == S - 1
                              else bwd_in.read())
                        gp, gx = vjps.pop(m)(gy)
                        grads = gp if grads is None else jax.tree.map(
                            lambda a, b: a + b, grads, gp
                        )
                        if s > 0:
                            bwd_out.write(gx)
                leaves = [np.asarray(g) for g in jax.tree.leaves(grads)]
                extra = {"stage": s}
                if s == S - 1:
                    extra["loss"] = loss_sum * inv_m
                result.write_tensors(leaves, extra=extra)
                executions += 1
        except ChannelClosed:
            # teardown (or a neighbor's failure closed an edge):
            # forward the close so the rest of the pipe unwedges
            for c in chans.values():
                if c is not None:
                    c.close()
            return executions
        except BaseException as e:  # rtlint: disable=RT005 — not
            # swallowed: surfaced to the driver as a typed result-
            # channel payload, then re-raised on the loop task
            logger.debug("pipeline stage %d failed: %s", s, e)
            if result is not None:
                try:
                    result.write_error(e)
                except Exception as e2:
                    logger.debug("stage %d error publish failed: %s", s, e2)
            for c in chans.values():
                if c is not None:
                    c.close()
            raise


class PipelineRef:
    """Future for one pipeline execute(); get() in execution order."""

    def __init__(self, pipe: "CompiledPipeline", idx: int):
        self._pipe = pipe
        self._idx = idx
        self._done = False
        self._value: Any = None
        self._error: Optional[BaseException] = None

    def get(self, timeout: Optional[float] = 120.0):
        if not self._done:
            self._pipe._collect_until(self._idx, timeout)
        if self._error is not None:
            raise self._error
        return self._value


class CompiledPipeline:
    """S stage actors + channel mesh + resident 1F1B loops.

    ``execute(x)`` splits x into M microbatches along axis 0, drives
    the pipe, and the returned ref's ``get()`` yields ``{"loss": float,
    "grads": [per-stage grad pytree]}`` — numerically equal (rtol 1e-5)
    to serial application + `jax.grad`, and to the in-program
    `parallel.pipeline_apply` schedule.
    """

    def __init__(self, stage_fn: Callable, stage_params: List[Any],
                 loss_fn: Callable, num_microbatches: int, *,
                 ring_slots: int = 2, max_inflight: int = 2,
                 stage_options: Optional[List[Dict]] = None):
        import ray_tpu as rt

        if num_microbatches < 1:
            raise ValueError("num_microbatches must be >= 1")
        self._S = len(stage_params)
        if self._S < 2:
            raise ValueError("a pipeline needs >= 2 stages")
        self._M = num_microbatches
        self._ring_slots = ring_slots
        self._max_inflight = max_inflight
        self._torn_down = False
        self._next_exec = 0
        self._next_collect = 0
        self._pending: Dict[int, PipelineRef] = {}
        # per-stage results read so far for the execution currently
        # being collected — a timeout resumes HERE instead of
        # re-reading stage 0 (which would desynchronize the channels)
        self._partial: List[Any] = []
        self._partial_loss: Optional[float] = None
        self._partial_error: Optional[BaseException] = None
        self._id = uuid.uuid4().hex[:8]

        import jax

        self._treedefs = [jax.tree.structure(p) for p in stage_params]

        Stage = rt.remote(_PipelineStage)
        self._actors = []
        for s in range(self._S):
            opts = (stage_options[s] if stage_options else {}) or {}
            cls = Stage.options(**opts) if opts else Stage
            self._actors.append(cls.remote(
                stage_fn, stage_params[s], s, self._S,
                loss_fn if s == self._S - 1 else None,
            ))
        # force placement before resolving ring locations
        rt.get([a.ping.remote() for a in self._actors], timeout=120)

        from ray_tpu.core.runtime import get_runtime
        from ray_tpu.dag.compiled_dag import resolve_actor_node

        driver = get_runtime().node_id
        nodes = [resolve_actor_node(a) for a in self._actors]

        def cname(tag: str) -> str:
            return f"pp{self._id}_{tag}"

        # input ring sized for a full batch of microbatches so
        # execute() rarely blocks mid-feed; the same size ships in
        # every stage plan (stage 0 may open — and thus create — the
        # ring first, and the creator's geometry wins)
        in_ring_slots = max(8, min(num_microbatches, 64))
        self._in_chan = Channel(cname("in"), nodes[0],
                                ring_slots=in_ring_slots)
        self._result_chans = [
            Channel(cname(f"r{s}"), driver) for s in range(self._S)
        ]
        plans = []
        for s in range(self._S):
            plan: Dict[str, Any] = {
                "num_microbatches": num_microbatches,
                "ring_slots": ring_slots,
                "in_ring_slots": in_ring_slots,
                "result": (cname(f"r{s}"), driver),
            }
            if s == 0:
                plan["in_chan"] = (cname("in"), nodes[0])
            else:
                plan["fwd_in"] = (cname(f"f{s - 1}"), nodes[s])
                plan["bwd_out"] = (cname(f"b{s - 1}"), nodes[s - 1])
            if s < self._S - 1:
                plan["fwd_out"] = (cname(f"f{s}"), nodes[s + 1])
                plan["bwd_in"] = (cname(f"b{s}"), nodes[s])
            plans.append(plan)
        self._edge_channels = []
        for s in range(self._S - 1):
            self._edge_channels.append((cname(f"f{s}"), nodes[s + 1]))
            self._edge_channels.append((cname(f"b{s}"), nodes[s]))
        self._loop_refs = [
            a.run.remote(p) for a, p in zip(self._actors, plans)
        ]
        self._loops_reaped: set = set()

    # -- execution -----------------------------------------------------
    def execute(self, x) -> PipelineRef:
        import numpy as np

        if self._torn_down:
            raise RuntimeError("pipeline was torn down")
        if len(self._pending) >= self._max_inflight:
            self._collect_until(self._next_collect, timeout=300.0)
        B = x.shape[0]
        if B % self._M:
            raise ValueError(
                f"batch {B} must divide into {self._M} microbatches"
            )
        mb = B // self._M
        host = np.asarray(x)
        for m in range(self._M):
            self._in_chan.write(host[m * mb:(m + 1) * mb])
        idx = self._next_exec
        self._next_exec += 1
        ref = PipelineRef(self, idx)
        self._pending[idx] = ref
        return ref

    def _check_loops(self):
        from ray_tpu import exceptions as exc
        from ray_tpu.dag.compiled_dag import reap_failed_loop_tasks

        for _ref, e in reap_failed_loop_tasks(self._loop_refs,
                                              self._loops_reaped):
            return exc.ActorDiedError(
                f"pipeline stage actor died mid-schedule: {e!r}"
            )
        return None

    def _read_result(self, ch: Channel, deadline: Optional[float]):
        while True:
            # a spent deadline still gets one minimal poll so get(0)
            # returns an already-published result instead of timing out
            step = 0.25 if deadline is None else min(
                0.25, max(0.001, deadline - time.monotonic())
            )
            try:
                return ch.read_tensors(timeout_s=step)
            except ChannelPollTimeout:
                dead = self._check_loops()
                if dead is not None:
                    raise dead from None
                if (deadline is not None
                        and time.monotonic() >= deadline):
                    raise TimeoutError(
                        "timed out waiting for pipeline result"
                    ) from None

    def _collect_until(self, idx: int, timeout: Optional[float]):
        import jax

        deadline = (None if timeout is None
                    else time.monotonic() + max(0.0, timeout))
        while self._next_collect <= idx:
            ref = self._pending.get(self._next_collect)
            while (self._partial_error is None
                   and len(self._partial) < self._S):
                s = len(self._partial)
                try:
                    leaves, extra = self._read_result(
                        self._result_chans[s], deadline
                    )
                except TimeoutError:
                    raise  # nothing lost: `_partial` resumes at stage s
                except ChannelClosed:
                    self._partial_error = RuntimeError(
                        "pipeline torn down mid-execution"
                    )
                    break  # a failed stage never publishes; don't hang
                except BaseException as e:  # rtlint: disable=RT005 — not
                    # swallowed: stored and re-raised by ref.get()
                    self._partial_error = e
                    break
                self._partial.append(jax.tree.unflatten(
                    self._treedefs[s], list(leaves)
                ))
                if extra and "loss" in extra:
                    self._partial_loss = float(extra["loss"])
            grads, loss, error = (
                self._partial, self._partial_loss, self._partial_error
            )
            self._partial, self._partial_loss, self._partial_error = (
                [], None, None
            )
            self._pending.pop(self._next_collect, None)
            self._next_collect += 1
            if ref is not None:
                ref._done = True
                ref._error = error
                ref._value = (None if error is not None
                              else {"loss": loss, "grads": grads})

    # -- lifecycle -----------------------------------------------------
    def teardown(self):
        if self._torn_down:
            return
        self._torn_down = True
        import ray_tpu as rt

        self._in_chan.close()
        try:
            _, still = rt.wait(self._loop_refs,
                               num_returns=len(self._loop_refs), timeout=10)
        except Exception as e:
            logger.debug("pipeline teardown wait failed: %s", e)
            still = list(self._loop_refs)
        if still:
            for name, loc in self._edge_channels:
                Channel(name, loc).close()
            for ch in self._result_chans:
                ch.close()
            try:
                rt.wait(still, num_returns=len(still), timeout=5)
            except Exception as e:
                logger.debug("pipeline second teardown wait failed: %s", e)
        for ch in [self._in_chan, *self._result_chans]:
            ch.destroy()
        for name, loc in self._edge_channels:
            Channel(name, loc).destroy()

    def __del__(self):
        try:
            self.teardown()
        except Exception:  # rtlint: disable=RT005 — interpreter-teardown
            pass  # destructor; logging machinery may already be gone


def compile_pipeline(stage_fn: Callable, stage_params: List[Any],
                     loss_fn: Callable, num_microbatches: int,
                     **kwargs) -> CompiledPipeline:
    """Build + launch a 1F1B pipeline (see CompiledPipeline)."""
    return CompiledPipeline(stage_fn, stage_params, loss_fn,
                            num_microbatches, **kwargs)
