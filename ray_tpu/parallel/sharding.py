"""Logical-axis sharding rules.

GSPMD-style: model code annotates parameters with *logical* axis names
("embed", "mlp", "heads", "vocab", ...), and a rule table maps logical
axes to mesh axes.  Changing the parallelism strategy is a rule-table
swap, not a model edit — the TP/FSDP equivalent of what the reference
only reaches through torch integrations (SURVEY §2.5: FSDP via
`prepare_model`, no first-class TP).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Default rules for a transformer sharded Megatron-style over tp with
# ZeRO-3-style param sharding over fsdp:
#   - embed dim is sharded over fsdp (params split for memory)
#   - mlp hidden + attention heads over tp (compute split)
#   - vocab over tp (output projection)
DEFAULT_RULES: Dict[str, Any] = {
    "batch": ("dp", "fsdp"),
    "seq": "sp",
    "embed": "fsdp",
    "mlp": "tp",
    "heads": "tp",
    "kv": None,
    "vocab": "tp",
    "expert": "ep",
    "stage": "pp",
    None: None,
}


def spec_from_logical(
    logical: Tuple[Optional[str], ...], rules: Optional[Dict] = None
) -> P:
    rules = rules or DEFAULT_RULES
    return P(*(rules.get(ax) for ax in logical))


def sharding_from_logical(
    mesh: Mesh, logical: Tuple[Optional[str], ...], rules: Optional[Dict] = None
) -> NamedSharding:
    return NamedSharding(mesh, spec_from_logical(logical, rules))


def tree_shardings(
    mesh: Mesh, logical_tree: Any, rules: Optional[Dict] = None
) -> Any:
    """Map a pytree of logical-axis tuples to NamedShardings."""
    return jax.tree.map(
        lambda logical: sharding_from_logical(mesh, tuple(logical), rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def shard_params(params: Any, mesh: Mesh, logical_tree: Any,
                 rules: Optional[Dict] = None) -> Any:
    """Device-put a parameter pytree according to logical rules."""
    shardings = tree_shardings(mesh, logical_tree, rules)
    return jax.tree.map(jax.device_put, params, shardings)


def infer_logical_like(params: Any, fallback=()) -> Any:
    """Fully-replicated logical tree matching `params` (for opt state
    scalars and anything without an annotation)."""
    return jax.tree.map(lambda _: tuple(fallback), params)


def optimizer_shardings(mesh: Mesh, opt, params: Any,
                        param_shardings: Any) -> Any:
    """Shardings for `opt.init(params)` state: a state leaf whose tree
    path ends with a parameter's path (optax state like Adam's mu/nu
    embeds the param tree) inherits that parameter's sharding; scalars
    and anything unrecognized replicate.  This is the ZeRO rule that
    keeps optimizer state sharded alongside fsdp params (SURVEY §2.5) —
    and it pins the state to the GLOBAL mesh device set, which matters
    under multi-process runtimes: a bare `jit(opt.init)` constant-folds
    the zeros and parks them uncommitted on the local default device.
    """
    state_shapes = jax.eval_shape(opt.init, params)
    p_leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    s_leaves = jax.tree_util.tree_flatten_with_path(param_shardings)[0]
    by_path = {
        tuple(pp): (tuple(pl.shape), sl)
        for (pp, pl), (_, sl) in zip(p_leaves, s_leaves)
    }
    replicated_ = NamedSharding(mesh, P())

    def pick(path, leaf):
        # longest matching path suffix wins (a short param path like
        # ('w',) can also be a suffix of a deeper, differently-sharded
        # one); O(depth) dict probes per state leaf
        for i in range(len(path)):
            hit = by_path.get(tuple(path[i:]))
            if hit is not None and tuple(leaf.shape) == hit[0]:
                return hit[1]
        return replicated_

    return jax.tree_util.tree_map_with_path(pick, state_shapes)
