"""Logical-axis sharding rules.

GSPMD-style: model code annotates parameters with *logical* axis names
("embed", "mlp", "heads", "vocab", ...), and a rule table maps logical
axes to mesh axes.  Changing the parallelism strategy is a rule-table
swap, not a model edit — the TP/FSDP equivalent of what the reference
only reaches through torch integrations (SURVEY §2.5: FSDP via
`prepare_model`, no first-class TP).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Default rules for a transformer sharded Megatron-style over tp with
# ZeRO-3-style param sharding over fsdp:
#   - embed dim is sharded over fsdp (params split for memory)
#   - mlp hidden + attention heads over tp (compute split)
#   - vocab over tp (output projection)
DEFAULT_RULES: Dict[str, Any] = {
    "batch": ("dp", "fsdp"),
    "seq": "sp",
    "embed": "fsdp",
    "mlp": "tp",
    "heads": "tp",
    "kv": None,
    "vocab": "tp",
    "expert": "ep",
    "stage": "pp",
    None: None,
}


def spec_from_logical(
    logical: Tuple[Optional[str], ...], rules: Optional[Dict] = None
) -> P:
    rules = rules or DEFAULT_RULES
    return P(*(rules.get(ax) for ax in logical))


def sharding_from_logical(
    mesh: Mesh, logical: Tuple[Optional[str], ...], rules: Optional[Dict] = None
) -> NamedSharding:
    return NamedSharding(mesh, spec_from_logical(logical, rules))


def tree_shardings(
    mesh: Mesh, logical_tree: Any, rules: Optional[Dict] = None
) -> Any:
    """Map a pytree of logical-axis tuples to NamedShardings."""
    return jax.tree.map(
        lambda logical: sharding_from_logical(mesh, tuple(logical), rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def shard_params(params: Any, mesh: Mesh, logical_tree: Any,
                 rules: Optional[Dict] = None) -> Any:
    """Device-put a parameter pytree according to logical rules."""
    shardings = tree_shardings(mesh, logical_tree, rules)
    return jax.tree.map(jax.device_put, params, shardings)


def infer_logical_like(params: Any, fallback=()) -> Any:
    """Fully-replicated logical tree matching `params` (for opt state
    scalars and anything without an annotation)."""
    return jax.tree.map(lambda _: tuple(fallback), params)
