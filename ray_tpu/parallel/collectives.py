"""Collective communication.

Two tiers, per SURVEY §5.8:

1. **In-program collectives** — inside jit/shard_map, `jax.lax`
   psum/all_gather/reduce_scatter/ppermute/all_to_all lower to XLA
   collectives on ICI/DCN.  Thin named wrappers here keep call sites
   uniform with the host tier.

2. **Host-level actor-group collectives** — the surface of the
   reference's `ray.util.collective` (`util/collective/collective.py:120`
   init_collective_group, `:258-615` allreduce/allgather/...), retargeted
   at numpy/jax host arrays.  Where the reference backs this with NCCL
   (cupy) or Gloo, here the rendezvous and data movement ride the
   framework's own object plane (a named rendezvous actor + shm objects)
   — device-resident arrays should use tier 1 instead, which is the
   TPU-native fast path.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

# ---- tier 1: in-program (imported lazily to keep core jax-free) ------


def psum(x, axis_name):
    from jax import lax

    return lax.psum(x, axis_name)


def pmean(x, axis_name):
    from jax import lax

    return lax.pmean(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    from jax import lax

    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0, tiled=True):
    from jax import lax

    return lax.psum_scatter(
        x, axis_name, scatter_dimension=scatter_dimension, tiled=tiled
    )


def ppermute(x, axis_name, perm):
    from jax import lax

    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
    from jax import lax

    return lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled
    )


# ---- tier 2: host-level actor-group collectives ----------------------

_REDUCERS = {
    "sum": lambda xs: sum(xs[1:], start=xs[0]),
    "mean": lambda xs: sum(xs[1:], start=xs[0]) / len(xs),
    "max": lambda xs: np.maximum.reduce(xs),
    "min": lambda xs: np.minimum.reduce(xs),
}

_PAIR_REDUCERS = {
    "sum": np.add,
    "mean": np.add,  # divided by world size at the end
    "max": np.maximum,
    "min": np.minimum,
}

# arrays at least this big take the ring path (bandwidth-optimal);
# below it the one-shot rendezvous exchange wins on latency
_RING_MIN_BYTES = 1 << 20


class _Rendezvous:
    """Named actor coordinating one collective group (the reference uses
    a named store actor for rendezvous the same way —
    `collective_group/nccl_collective_group.py` Rendezvous)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.rounds: Dict[int, Dict[int, Any]] = {}
        self.results: Dict[int, Any] = {}
        self.barrier_count: Dict[int, int] = {}
        self.mailbox: Dict[tuple, Any] = {}  # p2p: (seq, src, dst) -> value

    def contribute(self, round_id: int, rank: int, value, op: str):
        slot = self.rounds.setdefault(round_id, {})
        slot[rank] = value
        if len(slot) == self.world_size:
            xs = [slot[r] for r in range(self.world_size)]
            if op == "gather":
                self.results[round_id] = xs
            else:
                self.results[round_id] = _REDUCERS[op](xs)
            del self.rounds[round_id]
        return True

    def fetch(self, round_id: int):
        return self.results.get(round_id, _PENDING)

    def finish(self, round_id: int, rank: int):
        # last fetcher clears the slot
        c = self.barrier_count.get(round_id, 0) + 1
        if c >= self.world_size:
            self.results.pop(round_id, None)
            self.barrier_count.pop(round_id, None)
        else:
            self.barrier_count[round_id] = c

    def p2p_put(self, key: tuple, value):
        self.mailbox[key] = value
        return True

    def p2p_take(self, key: tuple):
        # pop-on-read: each (seq, src, dst) message is consumed once
        return self.mailbox.pop(key, _PENDING)


_PENDING = "__rt_pending__"


class CollectiveGroup:
    """Handle held by each member process/actor."""

    def __init__(self, group_name: str, world_size: int, rank: int):
        import ray_tpu as rt

        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self._round = 0
        name = f"__rt_collective__{group_name}"
        if rank == 0:
            self._rdv = rt.remote(_Rendezvous).options(
                name=name, num_cpus=0, max_concurrency=16
            ).remote(world_size)
        else:
            deadline = time.time() + 60
            while True:
                try:
                    self._rdv = rt.get_actor(name)
                    break
                except ValueError:
                    if time.time() > deadline:
                        raise
                    time.sleep(0.05)

    # -- ops (reference surface: collective.py:258-615) ---------------
    def _exchange(self, value, op: str):
        import ray_tpu as rt

        round_id = self._round
        self._round += 1
        rt.get(self._rdv.contribute.remote(round_id, self.rank, value, op))
        while True:
            out = rt.get(self._rdv.fetch.remote(round_id))
            if not (isinstance(out, str) and out == _PENDING):
                break
            time.sleep(0.002)
        self._rdv.finish.remote(round_id, self.rank)
        return out

    def allreduce(self, array, op: str = "sum"):
        arr = np.asarray(array)
        if (
            arr.nbytes >= _RING_MIN_BYTES
            and self.world_size > 1
            and op in _PAIR_REDUCERS
        ):
            return self._ring_allreduce(arr, op)
        return self._exchange(arr, op)

    def _ring_allreduce(self, arr, op: str):
        """Bandwidth-optimal ring allreduce (reduce-scatter +
        allgather; the NCCL algorithm the reference's collective group
        gets from `nccl_collective_group.py:175`).  Only CHUNK REFS
        travel through the rendezvous mailbox — the payloads move
        peer-to-peer over the object plane (shm + chunked daemon
        transfer), so per-rank traffic is 2·size·(N-1)/N instead of
        every byte funneling through one actor process."""
        import ray_tpu as rt

        n = self.world_size
        r = self.rank
        shape, dtype = arr.shape, arr.dtype
        flat = np.ascontiguousarray(arr).reshape(-1)
        acc = [c.astype(np.float64) if op == "mean" else c.copy()
               for c in np.array_split(flat, n)]
        right = (r + 1) % n
        left = (r - 1) % n
        reduce_pair = _PAIR_REDUCERS[op]

        held = []  # sender-side anchors: a chunk must outlive its
        # in-flight window (receiver's borrow registers asynchronously);
        # released after the closing barrier proves every recv landed

        def _send_chunk(chunk):
            # ship the REF (wrapped in a list: a bare ref as an
            # actor-call arg would materialize in the rendezvous);
            # payload stays in the object plane.  Bypasses send()'s
            # np.asarray coercion.
            ref = rt.put(chunk)
            held.append(ref)
            seq = self._p2p_next(r, right)
            rt.get(self._rdv.p2p_put.remote((seq, r, right), [ref]))

        def _recv_chunk():
            [ref] = self.recv(left)
            return rt.get(ref)

        # reduce-scatter: after n-1 steps rank r holds the fully
        # reduced chunk (r+1) mod n.  The reduce writes IN PLACE into
        # the accumulator chunk (acc entries are private copies): the
        # out-of-place form allocated + wrote a fresh chunk per step,
        # doubling memory traffic on the host tier's scarcest resource
        # (all ranks time-slice the same cores)
        # (safe unconditionally: every acc entry is a fresh writable
        # copy/astype, and all ranks run the identical dtype pipeline,
        # so received chunks always match the accumulator's dtype)
        for step in range(n - 1):
            _send_chunk(acc[(r - step) % n])
            recv_idx = (r - step - 1) % n
            tgt = acc[recv_idx]
            reduce_pair(tgt, _recv_chunk(), out=tgt)
        # allgather: circulate the reduced chunks
        for step in range(n - 1):
            _send_chunk(acc[(r - step + 1) % n])
            recv_idx = (r - step) % n
            acc[recv_idx] = _recv_chunk()
        out = np.concatenate(acc)
        self.barrier()  # every rank received: safe to drop `held`
        del held
        if op == "mean":
            out = out / n
            # float inputs keep their dtype (as the small path does);
            # integer means stay float so they never truncate
            if np.issubdtype(dtype, np.floating):
                out = out.astype(dtype, copy=False)
            return out.reshape(shape)
        return out.astype(dtype, copy=False).reshape(shape)

    def allgather(self, array) -> List:
        return self._exchange(np.asarray(array), "gather")

    def broadcast(self, array, src_rank: int = 0):
        out = self._exchange(np.asarray(array) if self.rank == src_rank else None,
                             "gather")
        return out[src_rank]

    def reducescatter(self, array, op: str = "sum"):
        full = self._exchange(np.asarray(array), op)
        chunks = np.array_split(full, self.world_size)
        return chunks[self.rank]

    def barrier(self):
        self._exchange(0, "sum")

    # -- p2p (reference surface: collective.py send:531 / recv:594) ---
    def _p2p_next(self, src: int, dst: int) -> int:
        seqs = getattr(self, "_p2p_seq", None)
        if seqs is None:
            seqs = self._p2p_seq = {}
        n = seqs.get((src, dst), 0)
        seqs[(src, dst)] = n + 1
        return n

    def send(self, array, dst_rank: int):
        """Post one array to dst_rank; pairs with its recv in program
        order per (src, dst) channel — both sides keep a pairwise
        sequence counter, so interleaved sends to different peers don't
        cross."""
        import ray_tpu as rt

        seq = self._p2p_next(self.rank, dst_rank)
        rt.get(self._rdv.p2p_put.remote(
            (seq, self.rank, dst_rank), np.asarray(array)
        ))

    def recv(self, src_rank: int, timeout_s: float = 60.0):
        """Blocking receive of the next message from src_rank.  The
        pairwise sequence advances only on success: a timed-out recv
        leaves the channel aligned, so a retry picks up the message the
        sender eventually posts."""
        import ray_tpu as rt

        seqs = getattr(self, "_p2p_seq", None)
        if seqs is None:
            seqs = self._p2p_seq = {}
        seq = seqs.get((src_rank, self.rank), 0)
        deadline = time.time() + timeout_s
        while True:
            out = rt.get(self._rdv.p2p_take.remote(
                (seq, src_rank, self.rank)
            ))
            if not (isinstance(out, str) and out == _PENDING):
                seqs[(src_rank, self.rank)] = seq + 1
                return out
            if time.time() > deadline:
                raise TimeoutError(
                    f"recv from rank {src_rank} timed out after {timeout_s}s"
                )
            time.sleep(0.002)


_groups: Dict[str, CollectiveGroup] = {}


def init_collective_group(
    world_size: int, rank: int, group_name: str = "default"
) -> CollectiveGroup:
    """Reference: `ray.util.collective.init_collective_group`
    (`collective.py:120`)."""
    g = CollectiveGroup(group_name, world_size, rank)
    _groups[group_name] = g
    return g


def get_group(group_name: str = "default") -> CollectiveGroup:
    return _groups[group_name]


def allreduce(array, group_name: str = "default", op: str = "sum"):
    return get_group(group_name).allreduce(array, op)


def allgather(array, group_name: str = "default"):
    return get_group(group_name).allgather(array)


def broadcast(array, src_rank: int = 0, group_name: str = "default"):
    return get_group(group_name).broadcast(array, src_rank)


def reducescatter(array, group_name: str = "default", op: str = "sum"):
    return get_group(group_name).reducescatter(array, op)


def barrier(group_name: str = "default"):
    get_group(group_name).barrier()


def send(array, dst_rank: int, group_name: str = "default"):
    get_group(group_name).send(array, dst_rank)


def recv(src_rank: int, group_name: str = "default", timeout_s: float = 60.0):
    return get_group(group_name).recv(src_rank, timeout_s)


def destroy_collective_group(group_name: str = "default"):
    g = _groups.pop(group_name, None)
    if g is not None and g.rank == 0:
        import ray_tpu as rt

        try:
            rt.kill(g._rdv)
        except Exception:
            pass
