"""Collective communication.

Two tiers, per SURVEY §5.8:

1. **In-program collectives** — inside jit/shard_map, `jax.lax`
   psum/all_gather/reduce_scatter/ppermute/all_to_all lower to XLA
   collectives on ICI/DCN.  Thin named wrappers here keep call sites
   uniform with the host tier.

2. **Host-level actor-group collectives** — the surface of the
   reference's `ray.util.collective` (`util/collective/collective.py:120`
   init_collective_group, `:258-615` allreduce/allgather/...), retargeted
   at numpy/jax host arrays.  Where the reference backs this with NCCL
   (cupy) or Gloo, here the rendezvous and data movement ride the
   framework's own object plane (a named rendezvous actor + shm objects)
   — device-resident arrays should use tier 1 instead, which is the
   TPU-native fast path.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

# ---- tier 1: in-program (imported lazily to keep core jax-free) ------


def psum(x, axis_name):
    from jax import lax

    return lax.psum(x, axis_name)


def pmean(x, axis_name):
    from jax import lax

    return lax.pmean(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    from jax import lax

    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0, tiled=True):
    from jax import lax

    return lax.psum_scatter(
        x, axis_name, scatter_dimension=scatter_dimension, tiled=tiled
    )


def ppermute(x, axis_name, perm):
    from jax import lax

    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
    from jax import lax

    return lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled
    )


# ---- tier 2: host-level actor-group collectives ----------------------

_REDUCERS = {
    "sum": lambda xs: sum(xs[1:], start=xs[0]),
    "mean": lambda xs: sum(xs[1:], start=xs[0]) / len(xs),
    "max": lambda xs: np.maximum.reduce(xs),
    "min": lambda xs: np.minimum.reduce(xs),
}


class _Rendezvous:
    """Named actor coordinating one collective group (the reference uses
    a named store actor for rendezvous the same way —
    `collective_group/nccl_collective_group.py` Rendezvous)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.rounds: Dict[int, Dict[int, Any]] = {}
        self.results: Dict[int, Any] = {}
        self.barrier_count: Dict[int, int] = {}

    def contribute(self, round_id: int, rank: int, value, op: str):
        slot = self.rounds.setdefault(round_id, {})
        slot[rank] = value
        if len(slot) == self.world_size:
            xs = [slot[r] for r in range(self.world_size)]
            if op == "gather":
                self.results[round_id] = xs
            else:
                self.results[round_id] = _REDUCERS[op](xs)
            del self.rounds[round_id]
        return True

    def fetch(self, round_id: int):
        return self.results.get(round_id, _PENDING)

    def finish(self, round_id: int, rank: int):
        # last fetcher clears the slot
        c = self.barrier_count.get(round_id, 0) + 1
        if c >= self.world_size:
            self.results.pop(round_id, None)
            self.barrier_count.pop(round_id, None)
        else:
            self.barrier_count[round_id] = c


_PENDING = "__rt_pending__"


class CollectiveGroup:
    """Handle held by each member process/actor."""

    def __init__(self, group_name: str, world_size: int, rank: int):
        import ray_tpu as rt

        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self._round = 0
        name = f"__rt_collective__{group_name}"
        if rank == 0:
            self._rdv = rt.remote(_Rendezvous).options(
                name=name, num_cpus=0, max_concurrency=16
            ).remote(world_size)
        else:
            deadline = time.time() + 60
            while True:
                try:
                    self._rdv = rt.get_actor(name)
                    break
                except ValueError:
                    if time.time() > deadline:
                        raise
                    time.sleep(0.05)

    # -- ops (reference surface: collective.py:258-615) ---------------
    def _exchange(self, value, op: str):
        import ray_tpu as rt

        round_id = self._round
        self._round += 1
        rt.get(self._rdv.contribute.remote(round_id, self.rank, value, op))
        while True:
            out = rt.get(self._rdv.fetch.remote(round_id))
            if not (isinstance(out, str) and out == _PENDING):
                break
            time.sleep(0.002)
        self._rdv.finish.remote(round_id, self.rank)
        return out

    def allreduce(self, array, op: str = "sum"):
        return self._exchange(np.asarray(array), op)

    def allgather(self, array) -> List:
        return self._exchange(np.asarray(array), "gather")

    def broadcast(self, array, src_rank: int = 0):
        out = self._exchange(np.asarray(array) if self.rank == src_rank else None,
                             "gather")
        return out[src_rank]

    def reducescatter(self, array, op: str = "sum"):
        full = self._exchange(np.asarray(array), op)
        chunks = np.array_split(full, self.world_size)
        return chunks[self.rank]

    def barrier(self):
        self._exchange(0, "sum")


_groups: Dict[str, CollectiveGroup] = {}


def init_collective_group(
    world_size: int, rank: int, group_name: str = "default"
) -> CollectiveGroup:
    """Reference: `ray.util.collective.init_collective_group`
    (`collective.py:120`)."""
    g = CollectiveGroup(group_name, world_size, rank)
    _groups[group_name] = g
    return g


def get_group(group_name: str = "default") -> CollectiveGroup:
    return _groups[group_name]


def allreduce(array, group_name: str = "default", op: str = "sum"):
    return get_group(group_name).allreduce(array, op)


def allgather(array, group_name: str = "default"):
    return get_group(group_name).allgather(array)


def broadcast(array, src_rank: int = 0, group_name: str = "default"):
    return get_group(group_name).broadcast(array, src_rank)


def reducescatter(array, group_name: str = "default", op: str = "sum"):
    return get_group(group_name).reducescatter(array, op)


def barrier(group_name: str = "default"):
    get_group(group_name).barrier()


def destroy_collective_group(group_name: str = "default"):
    g = _groups.pop(group_name, None)
    if g is not None and g.rank == 0:
        import ray_tpu as rt

        try:
            rt.kill(g._rdv)
        except Exception:
            pass
