"""Device mesh construction: the unit of compute placement.

TPU-first inversion of the reference's resource model (SURVEY §7): where
Ray schedules against scalar resource counts and approximates TPU pods
with a `TPU-{pod}-head` custom resource (`_private/accelerators/tpu.py:381`),
here an ICI-connected device mesh with named parallelism axes is the
first-class object.  All five parallelism strategies from SURVEY §2.5
are mesh axes:

    dp    pure data parallelism (params replicated)
    fsdp  sharded data parallelism (params/opt-state sharded, ZeRO-3)
    tp    tensor (Megatron-style layer) parallelism
    sp    sequence/context parallelism (ring attention rides this axis)
    ep    expert parallelism (MoE all-to-all)
    pp    pipeline parallelism (stage dimension)

`MeshSpec.build()` lays axes onto devices with `mesh_utils` so that the
fastest-varying axes (tp, sp) land on adjacent ICI neighbors.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "fsdp", "pp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape.  -1 on at most one axis means "all
    remaining devices".

    ``slices > 1`` builds a HYBRID multi-slice mesh (SURVEY §7 hard
    part: "multi-slice meshes over DCN"): devices are grouped into
    `slices` ICI-connected slices, and the slow DCN hops are confined to
    the data axes — the `dp` axis is split slice-major first (gradient
    allreduce is the one per-step collective that tolerates DCN
    latency), overflowing into `fsdp` only when dp alone cannot cover
    the slice count; tp/sp/ep/pp always stay inside one slice, where
    their per-layer collectives ride ICI.  Reference analog: the
    `TPU-{pod}-head` gang resource spanning pod slices
    (`_private/accelerators/tpu.py:381`) — here the topology is
    first-class in the compiler mesh, and `slice_device_groups` gives
    the runtime placement layer the same grouping so PGs and mesh agree.
    """

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1
    slices: int = 1

    def sizes(self) -> Dict[str, int]:
        return {
            "dp": self.dp,
            "fsdp": self.fsdp,
            "pp": self.pp,
            "ep": self.ep,
            "sp": self.sp,
            "tp": self.tp,
        }

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = self.sizes()
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError("at most one mesh axis may be -1")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        else:
            if fixed != n_devices:
                raise ValueError(
                    f"mesh {sizes} needs {fixed} devices, have {n_devices}"
                )
        return MeshSpec(
            **{k: sizes[k] for k in ("dp", "fsdp", "tp", "sp", "ep", "pp")},
            slices=self.slices,
        )

    def fit_to(self, n_devices: int) -> "MeshSpec":
        """Elastic re-fit: the widest spec for `n_devices` that keeps
        every MODEL axis (tp/sp/ep/pp) intact and shrinks only the data
        axes — dp first (pure replication, cheapest to lose), then fsdp.

        This is the shrink/re-grow contract of elastic training
        (ROADMAP item 4): a worker group that lost a host rebuilds a
        smaller mesh whose per-layer collectives are untouched, so the
        restored checkpoint reshards only along the batch/param-shard
        dimensions.  Wildcards (-1) resolve against `n_devices` as in
        `resolve`.  Raises when the model axes alone need more devices
        than remain."""
        if n_devices < 1:
            raise ValueError("n_devices must be >= 1")
        sizes = self.sizes()
        if any(v == -1 for v in sizes.values()):
            return self.resolve(n_devices)
        model = self.tp * self.sp * self.ep * self.pp
        if model > n_devices or n_devices % model != 0:
            raise ValueError(
                f"model axes tp*sp*ep*pp={model} cannot fit {n_devices} "
                f"device(s) without resharding a model dimension"
            )
        data = n_devices // model
        # shrink dp (pure replication) before fsdp: keeping the fsdp
        # degree as high as possible preserves the per-device param/
        # optimizer memory footprint the ZeRO sharding was sized for.
        # fsdp = largest divisor of the remaining data extent that does
        # not exceed the requested fsdp; dp covers the rest.
        fsdp = 1
        for cand in range(min(self.fsdp, data), 0, -1):
            if data % cand == 0:
                fsdp = cand
                break
        dp = data // fsdp
        fitted = MeshSpec(dp=dp, fsdp=fsdp, tp=self.tp, sp=self.sp,
                          ep=self.ep, pp=self.pp, slices=self.slices)
        if fitted.slices > 1:
            try:
                fitted.dcn_split()
            except ValueError:
                # the surviving data extent no longer factors across
                # the slice count (e.g. a whole slice was lost): the
                # re-formed mesh is single-slice by construction
                fitted = MeshSpec(dp=dp, fsdp=fsdp, tp=self.tp,
                                  sp=self.sp, ep=self.ep, pp=self.pp)
        return fitted

    def dcn_split(self) -> Tuple[int, int]:
        """(dcn_dp, dcn_fsdp): how the slice count factors across the
        data axes.  dp is split first; fsdp covers the remainder."""
        s = self.slices
        dcn_dp = math.gcd(self.dp, s)
        s //= dcn_dp
        dcn_fsdp = math.gcd(self.fsdp, s)
        s //= dcn_fsdp
        if s != 1:
            raise ValueError(
                f"slices={self.slices} does not divide dp*fsdp="
                f"{self.dp * self.fsdp} (tp/sp/ep/pp must stay inside "
                f"one ICI slice)"
            )
        return dcn_dp, dcn_fsdp

    def build(self, devices: Optional[Sequence] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        spec = self.resolve(len(devices))
        if spec.slices > 1:
            return spec._build_hybrid(devices)
        shape = tuple(spec.sizes()[a] for a in AXES)
        try:
            dev_array = mesh_utils.create_device_mesh(
                shape, devices=devices, allow_split_physical_axes=True
            )
        except Exception:
            # CPU/virtual meshes have no topology; plain reshape
            dev_array = np.array(devices).reshape(shape)
        return Mesh(dev_array, AXES)

    def _build_hybrid(self, devices: List) -> Mesh:
        """Slice-major hybrid mesh: DCN hops only along dp (then fsdp)."""
        n = len(devices)
        if n % self.slices != 0:
            raise ValueError(
                f"{n} devices not divisible by slices={self.slices}"
            )
        dcn_dp, dcn_fsdp = self.dcn_split()
        ici = dict(self.sizes())
        ici["dp"] //= dcn_dp
        ici["fsdp"] //= dcn_fsdp
        ici_shape = tuple(ici[a] for a in AXES)
        dcn_shape = tuple(
            {"dp": dcn_dp, "fsdp": dcn_fsdp}.get(a, 1) for a in AXES
        )
        real_slices = all(
            getattr(d, "slice_index", None) is not None for d in devices
        )
        try:
            # real TPUs: mesh_utils lays ICI axes onto the torus of each
            # slice and distributes dcn axes across slice granules
            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices,
                allow_split_physical_axes=True,
            )
        except Exception:
            if real_slices:
                # on real multislice hardware a failure here is a config
                # error — a contiguous-block guess could silently route
                # tp/sp collectives over DCN
                raise
            # virtual/CPU devices carry no slice topology: contiguous
            # blocks of n/slices devices stand in for slices
            groups = self.slice_device_groups(devices)
            arrs = []
            for g in groups:
                try:
                    arrs.append(mesh_utils.create_device_mesh(
                        ici_shape, devices=g, allow_split_physical_axes=True
                    ))
                except Exception:
                    arrs.append(np.array(g).reshape(ici_shape))
            # (dcn_dp, dcn_fsdp, ici_dp, ici_fsdp, pp, ep, sp, tp) ->
            # interleave so dp = dcn-major x ici, fsdp likewise
            stack = np.stack(arrs).reshape(dcn_dp, dcn_fsdp, *ici_shape)
            t = stack.transpose(0, 2, 1, 3, 4, 5, 6, 7)
            dev_array = t.reshape(tuple(self.sizes()[a] for a in AXES))
        return Mesh(dev_array, AXES)

    def slice_device_groups(self, devices: Optional[Sequence] = None) -> List[List]:
        """Per-slice device lists — the grouping the runtime placement
        layer must reproduce (one STRICT_PACK placement group per
        group) so compiler mesh and runtime PGs agree.  Uses the
        devices' `slice_index` when present (real multislice TPU);
        contiguous blocks otherwise."""
        devices = list(devices if devices is not None else jax.devices())
        by_slice: Dict[int, List] = {}
        if all(getattr(d, "slice_index", None) is not None for d in devices):
            for d in devices:
                by_slice.setdefault(d.slice_index, []).append(d)
            if len(by_slice) != self.slices:
                # never guess on real hardware: a contiguous fallback
                # would let runtime PGs straddle physical slices
                raise ValueError(
                    f"devices span {len(by_slice)} physical slices but "
                    f"spec.slices={self.slices}"
                )
            return [by_slice[k] for k in sorted(by_slice)]
        per = len(devices) // self.slices
        return [
            devices[i * per : (i + 1) * per] for i in range(self.slices)
        ]

    @staticmethod
    def data_parallel(n: int = -1) -> "MeshSpec":
        return MeshSpec(dp=n)

    @staticmethod
    def fsdp_only(n: int = -1) -> "MeshSpec":
        return MeshSpec(fsdp=n)


# ----------------------------------------------------------------------
# common shardings over a mesh
# ----------------------------------------------------------------------
def batch_axes() -> Tuple[str, ...]:
    """Axes over which the global batch is split."""
    return ("dp", "fsdp")


def data_sharding(mesh: Mesh, *trailing) -> NamedSharding:
    """Batch-dim sharded over (dp, fsdp); trailing dims as given."""
    return NamedSharding(mesh, P(batch_axes(), *trailing))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def local_batch_size(mesh: Mesh, global_batch: int) -> int:
    n = mesh.shape["dp"] * mesh.shape["fsdp"]
    if global_batch % n != 0:
        raise ValueError(f"global batch {global_batch} not divisible by {n}")
    return global_batch // n


def mesh_from_devices(n: Optional[int] = None, **axis_sizes) -> Mesh:
    devices = jax.devices()[: n or len(jax.devices())]
    return MeshSpec(**axis_sizes).build(devices)
