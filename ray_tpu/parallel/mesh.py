"""Device mesh construction: the unit of compute placement.

TPU-first inversion of the reference's resource model (SURVEY §7): where
Ray schedules against scalar resource counts and approximates TPU pods
with a `TPU-{pod}-head` custom resource (`_private/accelerators/tpu.py:381`),
here an ICI-connected device mesh with named parallelism axes is the
first-class object.  All five parallelism strategies from SURVEY §2.5
are mesh axes:

    dp    pure data parallelism (params replicated)
    fsdp  sharded data parallelism (params/opt-state sharded, ZeRO-3)
    tp    tensor (Megatron-style layer) parallelism
    sp    sequence/context parallelism (ring attention rides this axis)
    ep    expert parallelism (MoE all-to-all)
    pp    pipeline parallelism (stage dimension)

`MeshSpec.build()` lays axes onto devices with `mesh_utils` so that the
fastest-varying axes (tp, sp) land on adjacent ICI neighbors.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "fsdp", "pp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape.  -1 on at most one axis means "all
    remaining devices"."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1

    def sizes(self) -> Dict[str, int]:
        return {
            "dp": self.dp,
            "fsdp": self.fsdp,
            "pp": self.pp,
            "ep": self.ep,
            "sp": self.sp,
            "tp": self.tp,
        }

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = self.sizes()
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError("at most one mesh axis may be -1")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        else:
            if fixed != n_devices:
                raise ValueError(
                    f"mesh {sizes} needs {fixed} devices, have {n_devices}"
                )
        return MeshSpec(**{k: sizes[k] for k in ("dp", "fsdp", "tp", "sp", "ep", "pp")})

    def build(self, devices: Optional[Sequence] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        spec = self.resolve(len(devices))
        shape = tuple(spec.sizes()[a] for a in AXES)
        try:
            dev_array = mesh_utils.create_device_mesh(
                shape, devices=devices, allow_split_physical_axes=True
            )
        except Exception:
            # CPU/virtual meshes have no topology; plain reshape
            dev_array = np.array(devices).reshape(shape)
        return Mesh(dev_array, AXES)

    @staticmethod
    def data_parallel(n: int = -1) -> "MeshSpec":
        return MeshSpec(dp=n)

    @staticmethod
    def fsdp_only(n: int = -1) -> "MeshSpec":
        return MeshSpec(fsdp=n)


# ----------------------------------------------------------------------
# common shardings over a mesh
# ----------------------------------------------------------------------
def batch_axes() -> Tuple[str, ...]:
    """Axes over which the global batch is split."""
    return ("dp", "fsdp")


def data_sharding(mesh: Mesh, *trailing) -> NamedSharding:
    """Batch-dim sharded over (dp, fsdp); trailing dims as given."""
    return NamedSharding(mesh, P(batch_axes(), *trailing))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def local_batch_size(mesh: Mesh, global_batch: int) -> int:
    n = mesh.shape["dp"] * mesh.shape["fsdp"]
    if global_batch % n != 0:
        raise ValueError(f"global batch {global_batch} not divisible by {n}")
    return global_batch // n


def mesh_from_devices(n: Optional[int] = None, **axis_sizes) -> Mesh:
    devices = jax.devices()[: n or len(jax.devices())]
    return MeshSpec(**axis_sizes).build(devices)
