"""Parallelism layer: meshes, shardings, collectives, sequence/pipeline/expert parallelism."""

from ray_tpu.parallel.mesh import (
    AXES,
    MeshSpec,
    batch_axes,
    data_sharding,
    local_batch_size,
    mesh_from_devices,
    replicated,
)
from ray_tpu.parallel.moe import MoEConfig, init_moe, moe_forward
from ray_tpu.parallel.pipeline import pipeline_apply, stage_sharding
from ray_tpu.parallel.pipeline_dag import (
    CompiledPipeline,
    bubble_fraction,
    compile_pipeline,
    one_f1b_schedule,
)
from ray_tpu.parallel.sharding import (
    DEFAULT_RULES,
    optimizer_shardings,
    shard_params,
    sharding_from_logical,
    spec_from_logical,
    tree_shardings,
)

__all__ = [
    "AXES",
    "CompiledPipeline",
    "DEFAULT_RULES",
    "MeshSpec",
    "MoEConfig",
    "batch_axes",
    "bubble_fraction",
    "compile_pipeline",
    "one_f1b_schedule",
    "data_sharding",
    "init_moe",
    "local_batch_size",
    "mesh_from_devices",
    "moe_forward",
    "optimizer_shardings",
    "pipeline_apply",
    "replicated",
    "shard_params",
    "sharding_from_logical",
    "stage_sharding",
    "spec_from_logical",
    "tree_shardings",
]
