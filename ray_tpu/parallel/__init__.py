"""Parallelism layer: meshes, shardings, collectives, sequence parallelism."""

from ray_tpu.parallel.mesh import (
    AXES,
    MeshSpec,
    batch_axes,
    data_sharding,
    local_batch_size,
    mesh_from_devices,
    replicated,
)
from ray_tpu.parallel.sharding import (
    DEFAULT_RULES,
    shard_params,
    sharding_from_logical,
    spec_from_logical,
    tree_shardings,
)

__all__ = [
    "AXES",
    "DEFAULT_RULES",
    "MeshSpec",
    "batch_axes",
    "data_sharding",
    "local_batch_size",
    "mesh_from_devices",
    "replicated",
    "shard_params",
    "sharding_from_logical",
    "spec_from_logical",
    "tree_shardings",
]
