"""Mixture-of-experts with expert parallelism over the `ep` mesh axis.

Absent from the reference (SURVEY §2.5: EP/MoE "Absent — build: expert
mesh axis + ragged all-to-all").  Design: top-k token routing with a
capacity factor; tokens are dispatched to their experts' devices with
`lax.all_to_all` over `ep` inside `shard_map`, each device runs its
resident experts' FFN as one batched matmul (MXU-friendly fixed
capacity slots — dropped tokens pass through the residual), results
return via the inverse all-to-all and combine weighted by router probs.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    dim: int
    hidden: int
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16


def init_moe(cfg: MoEConfig, key: jax.Array) -> Dict:
    k1, k2, k3 = jax.random.split(key, 3)
    E, Dm, Dh = cfg.num_experts, cfg.dim, cfg.hidden
    std = 0.02
    return {
        "router": jax.random.normal(k1, (Dm, E), jnp.float32) * std,
        "w_in": jax.random.normal(k2, (E, Dm, Dh), jnp.float32) * std,
        "w_out": jax.random.normal(k3, (E, Dh, Dm), jnp.float32) * std,
    }


def moe_logical_axes(cfg: MoEConfig) -> Dict:
    return {
        "router": ("embed", None),
        "w_in": ("expert", "embed", "mlp"),
        "w_out": ("expert", "mlp", "embed"),
    }


def _capacity(tokens_per_device: int, cfg: MoEConfig, ep: int) -> int:
    cap = int(cfg.capacity_factor * tokens_per_device * cfg.top_k
              / cfg.num_experts)
    return max(cap, 4)


def moe_forward(cfg: MoEConfig, params: Dict, x: jax.Array,
                mesh: Optional[Mesh] = None) -> Tuple[jax.Array, Dict]:
    """x [B, T, D] -> (out [B, T, D], aux {load_balance_loss}).

    Without a mesh (or ep=1) this is the single-device dense-dispatch
    path; with an `ep` axis the same math runs under shard_map with
    all_to_all token exchange.
    """
    if mesh is not None and mesh.shape.get("ep", 1) > 1:
        return _moe_forward_ep(cfg, params, x, mesh)
    return _moe_forward_local(cfg, params, x)


def _route(cfg: MoEConfig, router_w, x2d):
    """Top-k routing; returns (probs [N, k], idx [N, k], aux loss)."""
    logits = (x2d.astype(jnp.float32) @ router_w)  # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_i = lax.top_k(probs, cfg.top_k)
    top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
    # Switch-style load-balance loss: frac of tokens per expert x mean prob
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(
        jax.nn.one_hot(top_i[:, 0], cfg.num_experts, dtype=jnp.float32), axis=0
    )
    aux = cfg.num_experts * jnp.sum(me * ce)
    return top_p, top_i, aux


def _expert_ffn(cfg: MoEConfig, w_in, w_out, slots):
    """slots [E_local, C, D] -> [E_local, C, D]; one batched matmul per
    projection (the MXU-friendly shape)."""
    h = jnp.einsum("ecd,edh->ech", slots.astype(cfg.dtype),
                   w_in.astype(cfg.dtype))
    h = jax.nn.gelu(h)
    return jnp.einsum("ech,ehd->ecd", h, w_out.astype(cfg.dtype))


def _dispatch(cfg: MoEConfig, x2d, top_p, top_i, capacity: int):
    """Build fixed-capacity expert slots.  Returns (slots [E, C, D],
    slot_pos [N, k], keep [N, k]); combine weights come from the router
    probs in _combine."""
    N = x2d.shape[0]
    E, C = cfg.num_experts, capacity
    # position of each (token, k) within its expert's slot list
    flat_i = top_i.reshape(-1)  # [N*k]
    one_hot = jax.nn.one_hot(flat_i, E, dtype=jnp.int32)  # [N*k, E]
    pos_in_expert = jnp.cumsum(one_hot, axis=0) - one_hot
    slot = jnp.sum(pos_in_expert * one_hot, axis=-1)  # [N*k]
    keep = slot < C
    slots = jnp.zeros((E, C, x2d.shape[1]), x2d.dtype)
    flat_tok = jnp.repeat(jnp.arange(N), cfg.top_k)
    slots = slots.at[
        jnp.where(keep, flat_i, 0), jnp.where(keep, slot, 0)
    ].add(jnp.where(keep[:, None], x2d[flat_tok], 0))
    return slots, slot.reshape(N, cfg.top_k), keep.reshape(N, cfg.top_k)


def _combine(cfg: MoEConfig, out_slots, top_p, top_i, slot_pos, keep, N):
    flat_i = top_i.reshape(-1)
    flat_s = slot_pos.reshape(-1)
    flat_keep = keep.reshape(-1)
    gathered = out_slots[flat_i, flat_s]  # [N*k, D]
    gathered = jnp.where(flat_keep[:, None], gathered, 0)
    weighted = gathered * top_p.reshape(-1)[:, None].astype(gathered.dtype)
    return weighted.reshape(N, cfg.top_k, -1).sum(axis=1)


def _moe_forward_local(cfg: MoEConfig, params: Dict, x: jax.Array):
    B, T, D = x.shape
    x2d = x.reshape(B * T, D)
    top_p, top_i, aux = _route(cfg, params["router"], x2d)
    cap = _capacity(B * T, cfg, ep=1)
    slots, slot_pos, keep = _dispatch(cfg, x2d, top_p, top_i, cap)
    out_slots = _expert_ffn(cfg, params["w_in"], params["w_out"], slots)
    out = _combine(cfg, out_slots, top_p, top_i, slot_pos, keep, B * T)
    return out.reshape(B, T, D).astype(x.dtype), {"load_balance_loss": aux}


def _moe_forward_ep(cfg: MoEConfig, params: Dict, x: jax.Array, mesh: Mesh):
    ep = mesh.shape["ep"]
    assert cfg.num_experts % ep == 0, "num_experts must divide ep"
    e_local = cfg.num_experts // ep

    def body(router_w, w_in, w_out, xs):
        # xs: this device's token shard [b, T, D]
        b, T, D = xs.shape
        x2d = xs.reshape(b * T, D)
        top_p, top_i, aux = _route(cfg, router_w, x2d)
        cap = _capacity(b * T, cfg, ep)
        slots, slot_pos, keep = _dispatch(cfg, x2d, top_p, top_i, cap)
        # slots [E, C, D] -> exchange: each device keeps rows for its
        # resident experts from EVERY peer: [E, C, D] -> [ep, e_local, C, D]
        slots = slots.reshape(ep, e_local, cap, D)
        # all_to_all over ep: axis 0 splits, results concatenate on a
        # new leading axis -> [ep(peers), e_local, C, D]
        recv = lax.all_to_all(slots, "ep", split_axis=0, concat_axis=0,
                              tiled=False)
        # run resident experts over all peers' tokens: fold the peer dim
        # into capacity so each resident expert runs ONE matmul over
        # peer*C rows — no weight replication
        peer, el = recv.shape[0], recv.shape[1]
        stacked = recv.transpose(1, 0, 2, 3).reshape(el, peer * cap, D)
        out = _expert_ffn(cfg, w_in, w_out, stacked)
        out = out.reshape(el, peer, cap, D).transpose(1, 0, 2, 3)
        # return to owners: inverse all_to_all
        back = lax.all_to_all(out, "ep", split_axis=0, concat_axis=0,
                              tiled=False)
        out_slots = back.reshape(cfg.num_experts, cap, D)
        combined = _combine(cfg, out_slots, top_p, top_i, slot_pos, keep,
                            b * T)
        return combined.reshape(b, T, D).astype(xs.dtype), aux.reshape(1)

    in_specs = (
        P(), P("ep"), P("ep"),  # router replicated; experts sharded on ep
        P("ep"),  # tokens sharded over ep (data-parallel style)
    )
    out_specs = (P("ep"), P("ep"))
    fn = shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )
    out, aux = fn(params["router"], params["w_in"], params["w_out"], x)
    return out, {"load_balance_loss": jnp.mean(aux)}
