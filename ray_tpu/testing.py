"""Fault-injection utilities for tests and chaos runs.

Reference: `python/ray/_private/test_utils.py` — `WorkerKillerActor`
(:1597), `RayletKiller` (:1536), `ResourceKillerActor` (:1433): actors
that kill cluster components on a cadence while a workload runs, the
substrate of the reference's chaos suites
(`release/nightly_tests/setup_chaos.py`).  Single-host clusters (the
`cluster_utils.Cluster` test shape) let killers deliver straight
SIGKILLs by pid.
"""

from __future__ import annotations

import os
import random
import signal
import time
from typing import List, Optional

import ray_tpu as rt


def list_workers() -> List[dict]:
    """All pool workers on the local node (id, pid, kind, idle)."""
    from ray_tpu.core.runtime import get_runtime

    return get_runtime().noded_call("list_workers") or []


def kill_random_worker(*, busy_only: bool = True,
                       exclude_actors: bool = True,
                       rng: Optional[random.Random] = None) -> Optional[int]:
    """SIGKILL one worker; returns the pid or None if no candidate.
    The runtime's worker-death path turns this into retriable task
    failures / actor restarts — the property chaos tests assert."""
    rng = rng or random
    candidates = [
        w for w in list_workers()
        if w["kind"] == "worker"
        and (not busy_only or not w["idle"])
        and (not exclude_actors or w["actor_id"] is None)
        and w["pid"] != os.getpid()
    ]
    if not candidates:
        return None
    victim = rng.choice(candidates)
    try:
        os.kill(victim["pid"], signal.SIGKILL)
    except ProcessLookupError:
        return None
    return victim["pid"]


@rt.remote(max_concurrency=2)  # stop() must interleave with run()
class WorkerKiller:
    """Resident killer: SIGKILLs a random busy task worker every
    `interval_s` until stopped (reference: WorkerKillerActor)."""

    def __init__(self, interval_s: float = 0.5, seed: int = 0):
        self.interval_s = interval_s
        self.rng = random.Random(seed)
        self.killed: List[int] = []
        self._stop = False

    def run(self, duration_s: float = 10.0) -> List[int]:
        deadline = time.time() + duration_s
        while not self._stop and time.time() < deadline:
            pid = kill_random_worker(rng=self.rng)
            if pid is not None:
                self.killed.append(pid)
            time.sleep(self.interval_s)
        return self.killed

    def stop(self) -> List[int]:
        self._stop = True
        return self.killed
