"""Fault-injection utilities for tests and chaos runs.

Reference: `python/ray/_private/test_utils.py` — `WorkerKillerActor`
(:1597), `RayletKiller` (:1536), `ResourceKillerActor` (:1433): actors
that kill cluster components on a cadence while a workload runs, the
substrate of the reference's chaos suites
(`release/nightly_tests/setup_chaos.py`).  Single-host clusters (the
`cluster_utils.Cluster` test shape) let killers deliver straight
SIGKILLs by pid.
"""

from __future__ import annotations

import os
import random
import signal
import time
from typing import List, Optional

import ray_tpu as rt


def list_workers() -> List[dict]:
    """All pool workers on the local node (id, pid, kind, idle)."""
    from ray_tpu.core.runtime import get_runtime

    return get_runtime().noded_call("list_workers") or []


def kill_random_worker(*, busy_only: bool = True,
                       exclude_actors: bool = True,
                       rng: Optional[random.Random] = None) -> Optional[int]:
    """SIGKILL one worker; returns the pid or None if no candidate.
    The runtime's worker-death path turns this into retriable task
    failures / actor restarts — the property chaos tests assert."""
    rng = rng or random
    candidates = [
        w for w in list_workers()
        if w["kind"] == "worker"
        and (not busy_only or not w["idle"])
        and (not exclude_actors or w["actor_id"] is None)
        and w["pid"] != os.getpid()
    ]
    if not candidates:
        return None
    victim = rng.choice(candidates)
    try:
        os.kill(victim["pid"], signal.SIGKILL)
    except ProcessLookupError:
        return None
    return victim["pid"]


@rt.remote(max_concurrency=2)  # stop() must interleave with run()
class WorkerKiller:
    """Resident killer: SIGKILLs a random busy task worker every
    `interval_s` until stopped (reference: WorkerKillerActor)."""

    def __init__(self, interval_s: float = 0.5, seed: int = 0):
        self.interval_s = interval_s
        self.rng = random.Random(seed)
        self.killed: List[int] = []
        self._stop = False

    def run(self, duration_s: float = 10.0) -> List[int]:
        deadline = time.time() + duration_s
        while not self._stop and time.time() < deadline:
            pid = kill_random_worker(rng=self.rng)
            if pid is not None:
                self.killed.append(pid)
            time.sleep(self.interval_s)
        return self.killed

    def stop(self) -> List[int]:
        self._stop = True
        return self.killed


# ----------------------------------------------------------------------
# environment capability probes (skip-guards for tier-1)
# ----------------------------------------------------------------------
_MULTIPROC_PROBE = r"""
import sys
import jax

jax.config.update("jax_platforms", "cpu")  # sitecustomize may bake axon
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass  # older/newer flag surface: probe the default wiring instead
rank, port = int(sys.argv[1]), sys.argv[2]
jax.distributed.initialize(
    f"127.0.0.1:{port}", num_processes=2, process_id=rank
)
import jax.numpy as jnp
from jax.experimental import multihost_utils

out = multihost_utils.process_allgather(jnp.ones((2,)) * (rank + 1))
assert float(out.sum()) == 6.0, out
"""

_multiproc_cpu_cache: Optional[tuple] = None


def jax_multiprocess_cpu_support() -> tuple:
    """(supported, reason): can this JAX/jaxlib run MULTI-PROCESS
    computations on the CPU backend (2 OS processes forming one global
    mesh via `jax.distributed`, the shape `test_train_distributed`
    miniaturizes)?  Some jaxlib builds compile the CPU client without
    cross-process collectives and fail any spanning computation with
    "Multiprocess computations aren't implemented on the CPU backend" —
    an environment limit, not a code path worth failing tier-1 over.
    Probes once per process with a real 2-process allgather."""
    global _multiproc_cpu_cache
    if _multiproc_cpu_cache is not None:
        return _multiproc_cpu_cache
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _MULTIPROC_PROBE, str(rank), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for rank in (0, 1)
    ]
    ok, reason = True, ""
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            # one hung rendezvous means the pair is dead: kill BOTH
            # now so the second communicate() can't burn another 120 s
            for q in procs:
                q.kill()
            out, _ = p.communicate()
            ok, reason = False, "probe timed out (rendezvous hung)"
            continue
        if p.returncode != 0:
            ok = False
            if not reason:  # keep the FIRST cause: a later process
                # killed after a timeout would clobber it with SIGKILL
                tail = [ln for ln in (out or "").splitlines()
                        if ln.strip()]
                reason = (tail[-1][-200:] if tail
                          else f"exit {p.returncode}")
    _multiproc_cpu_cache = (ok, reason)
    return _multiproc_cpu_cache


_pallas_cache: dict = {}


def pallas_kernel_support(kind: str = "attention") -> tuple:
    """(supported, reason): can this JAX build trace and execute the
    repo's Pallas TPU kernels (interpret mode on CPU)?  Kernel tests
    skip-guard on this instead of failing tier-1 when the environment's
    Pallas API surface is missing or incompatible.  `kind` selects the
    kernel family actually probed ("attention" | "xent" | "paged")."""
    if kind in _pallas_cache:
        return _pallas_cache[kind]
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        if kind == "attention":
            from ray_tpu.ops import flash_attention

            q = jnp.ones((1, 16, 1, 16), jnp.float32) * 0.1
            out = flash_attention(q, q, q, True, 16, 16, True)
            np.asarray(out)
        elif kind == "xent":
            from ray_tpu.ops.xent_pallas import pallas_cross_entropy

            x = jnp.ones((8, 16), jnp.float32) * 0.1
            w = jnp.ones((16, 16), jnp.float32) * 0.1
            tg = jnp.zeros((8,), jnp.int32)
            np.asarray(pallas_cross_entropy(x, w, tg, 8, 16))
        elif kind == "paged":
            # both paged kernels end-to-end: scalar-prefetch block
            # tables, aliased in-place append, online-softmax walk
            from ray_tpu.ops.paged_attention import (
                paged_decode_attention, paged_kv_append,
            )

            kp = jnp.zeros((1, 3, 4, 1, 16), jnp.float32)
            vp = jnp.zeros_like(kp)
            tables = jnp.asarray([[1, 2]], jnp.int32)
            pos = jnp.asarray([5], jnp.int32)
            row = jnp.ones((1, 1, 16), jnp.float32) * 0.1
            kp, vp = paged_kv_append(kp, vp, row, row, tables, pos, 0)
            q = jnp.ones((1, 2, 16), jnp.float32) * 0.1
            out = paged_decode_attention(q, kp, vp, tables, pos, 0)
            assert np.asarray(out).shape == (1, 2, 16)
        else:
            raise ValueError(f"unknown kernel probe kind: {kind}")
        result = (True, "")
    except Exception as e:  # rtlint: disable=RT005 — not swallowed:
        # the failure IS the probe's result, surfaced in skip reasons
        result = (False, f"{type(e).__name__}: {e}")
    _pallas_cache[kind] = result
    return result
