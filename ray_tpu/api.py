"""Public API: init/shutdown, @remote, get/put/wait, actors.

Surface mirrors the reference's `python/ray/_private/worker.py` public
functions (`ray.init:1240`, `get:2601`, `put:2737`, `wait:2802`,
`ray.remote:3191`) and `remote_function.py` / `actor.py` decorator
products, so reference users find the same call shapes.
"""

from __future__ import annotations

import atexit
import inspect
import json
import os
import subprocess
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ray_tpu import exceptions as exc
from ray_tpu.core.config import Config, set_config
from ray_tpu.core.ids import ActorID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.runtime import (
    ObjectRefGenerator,
    Runtime,
    get_runtime,
    is_initialized,
    set_runtime,
)

_session: Dict[str, Any] = {}
_init_lock = threading.Lock()


# ----------------------------------------------------------------------
# init / shutdown
# ----------------------------------------------------------------------
def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    num_workers: Optional[int] = None,
    resources: Optional[Dict[str, float]] = None,
    object_store_memory: Optional[int] = None,
    ignore_reinit_error: bool = False,
    log_to_driver: Optional[bool] = None,
    _system_config: Optional[Dict[str, Any]] = None,
    **_kwargs,
):
    """Start (or connect to) a cluster.

    With no address, boots a single-node cluster: a node daemon process
    (hosting the controller) plus a worker pool, then connects this
    process as the driver — the same shape as the reference's
    `ray.init()` auto-start (`_private/worker.py:1240` + `node.py:37`).
    """
    with _init_lock:
        if is_initialized():
            if ignore_reinit_error:
                return _session.get("info")
            raise exc.RayTpuError("ray_tpu.init() called twice")

        # workers spawned anywhere in this session adopt the driver's
        # sys.path (see worker_main) so by-reference pickles resolve
        import sys as _sys

        os.environ["RT_DRIVER_SYS_PATH"] = json.dumps(
            [p for p in _sys.path if p]
        )

        cfg = Config().apply_env_overrides()
        if _system_config:
            cfg.apply_dict(_system_config)
        if log_to_driver is not None:
            cfg.log_to_driver = log_to_driver
        if object_store_memory:
            cfg.object_store_memory = object_store_memory
        set_config(cfg)

        if address is None:
            from ray_tpu.core.node_launcher import launch_noded
            from ray_tpu.shm import sweep_stale_segments

            # reap segments orphaned by hard-killed prior clusters
            # before this one sizes its own store (daemon boot sweeps
            # too — this covers drivers racing the daemon's first boot)
            sweep_stale_segments()
            session_dir = _make_session_dir()
            proc, info = launch_noded(
                session_dir,
                head=True,
                num_cpus=num_cpus,
                num_tpus=num_tpus,
                resources=resources,
                num_workers=num_workers or 0,
                env_extra=cfg.to_env(),
            )
            _session["noded_proc"] = proc
            _session["session_dir"] = session_dir
        else:
            # join an existing cluster: address is the head ready-file
            # or "host:port" of the controller plus a local socket
            info = _resolve_address(address)

        rt = Runtime("driver")
        rt.start(info["socket_path"], tuple(info["controller_addr"]))
        set_runtime(rt)
        rt.controller_call(
            "register_job", {"job_id": rt.job_id.hex(), "pid": os.getpid()}
        )
        # joining drivers can't reach pre-existing workers through the
        # spawn env — publish sys.path in the KV too; executors extend
        # their path from it on ModuleNotFoundError and retry
        rt.kv_put(
            "driver:sys_path",
            json.dumps([p for p in _sys.path if p]).encode(),
        )
        _session["info"] = info
        atexit.register(shutdown)
        return info


def _make_session_dir() -> str:
    base = os.environ.get("RT_TMPDIR", "/tmp/ray_tpu")
    d = os.path.join(base, f"session_{int(time.time())}_{os.getpid()}")
    os.makedirs(os.path.join(d, "logs"), exist_ok=True)
    return d


def _wait_ready(ready_file: str, proc, timeout: float = 60.0) -> Dict:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if proc.poll() is not None:
            raise exc.RayTpuError(
                f"node daemon exited with {proc.returncode}; see session logs"
            )
        if os.path.exists(ready_file):
            with open(ready_file) as f:
                return json.load(f)
        time.sleep(0.02)
    raise exc.RayTpuError("timed out waiting for the node daemon to start")


def _resolve_address(address: str) -> Dict:
    if address == "auto":
        # newest live session on this host (reference: ray.init("auto")
        # via the bootstrap address file)
        import glob

        base = os.environ.get("RT_TMPDIR", "/tmp/ray_tpu")

        def _mtime(p):
            try:
                return os.path.getmtime(p)
            except OSError:
                return 0.0  # deleted between glob and sort

        candidates = sorted(
            glob.glob(os.path.join(base, "session_*", "ready.json"))
            + glob.glob(os.path.join(base, "cluster_*", "node_*", "ready.json")),
            key=_mtime,
            reverse=True,
        )
        import socket as _socket

        for path in candidates:
            try:
                with open(path) as f:
                    info = json.load(f)
                # liveness = an accepting socket, not a leftover file
                # (SIGKILLed daemons never unlink theirs)
                s = _socket.socket(_socket.AF_UNIX)
                s.settimeout(1.0)
                try:
                    s.connect(info["socket_path"])
                finally:
                    s.close()
                return info
            except (OSError, ValueError, KeyError):
                continue
        raise exc.RayTpuError("address='auto': no live cluster found")
    if os.path.exists(address):
        with open(address) as f:
            return json.load(f)
    raise exc.RayTpuError(
        "address must be a ready-file path of a running cluster (or 'auto')"
    )


def shutdown():
    if is_initialized():
        rt = get_runtime()
        rt.shutdown()
        set_runtime(None)
    # circuit-breaker state is per-cluster-session: replica/worker ids
    # can recur across init cycles in one process, and a stale open
    # breaker must not eject a fresh session's healthy peers
    from ray_tpu.core import rpc as _rpc

    _rpc.reset_breakers()
    proc = _session.pop("noded_proc", None)
    if proc is not None:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()
    _session.pop("info", None)


def is_started() -> bool:
    return is_initialized()


# ----------------------------------------------------------------------
# object API
# ----------------------------------------------------------------------
def put(value: Any, *, inline: Optional[bool] = None) -> ObjectRef:
    """Store an object and return its ref.  `inline=False` forces the
    shm path even for small objects — the broadcast shape: node-local
    borrowers read zero-copy instead of issuing a per-borrower owner
    RPC (see Runtime.put)."""
    return get_runtime().put(value, inline=inline)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]], *, timeout: Optional[float] = None):
    if isinstance(refs, ObjectRefGenerator):
        raise TypeError(
            "get() does not accept an ObjectRefGenerator — iterate it "
            "and get() each yielded ObjectRef (reference: ray.get raises "
            "the same way on streaming generators)"
        )
    return get_runtime().get(refs, timeout=timeout)


def wait(
    refs: List[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
):
    return get_runtime().wait(refs, num_returns, timeout, fetch_local)


# ----------------------------------------------------------------------
# remote functions
# ----------------------------------------------------------------------
class RemoteFunction:
    """Product of @remote on a function (reference:
    `remote_function.py:40`)."""

    def __init__(self, fn, options: Dict[str, Any]):
        self._fn = fn
        self._options = options
        self.__name__ = getattr(fn, "__name__", "remote_function")

    def remote(self, *args, **kwargs):
        opts = self._options
        n = opts.get("num_returns", 1)
        if n == 1 and (inspect.isgeneratorfunction(self._fn)
                       or inspect.isasyncgenfunction(self._fn)):
            # generator functions stream by default (reference:
            # streaming generators in `_raylet.pyx` / task_manager.h:208)
            opts = dict(opts)
            n = opts["num_returns"] = "streaming"
        out = get_runtime().submit_task(self._fn, list(args), kwargs, **opts)
        if n == "streaming":
            return out  # ObjectRefGenerator
        return out[0] if n == 1 else out

    def bind(self, *args, **kwargs):
        """Build a task-DAG node instead of executing (reference:
        `dag/dag_node.py:29`; workflows execute these durably)."""
        from ray_tpu.dag.dag_node import FunctionNode

        return FunctionNode(self, args, kwargs)

    def options(self, **opts) -> "RemoteFunction":
        """Per-call overrides (reference: `.options()` on remote
        functions).  Notable keys: `num_cpus`/`num_tpus`/`resources`,
        `max_retries`, `retry_exceptions`, `num_returns`, scheduling
        strategies — and `timeout_s`, an END-TO-END deadline: the call
        (including retries and any nested `.remote()` calls it makes,
        which inherit the remaining budget) fails with
        `DeadlineExceededError` once the budget is spent."""
        _validate_timeout_s(opts)
        merged = dict(self._options)
        merged.update(opts)
        return RemoteFunction(self._fn, merged)

    def __call__(self, *a, **k):
        raise TypeError(
            f"Remote function cannot be called directly; use "
            f"{self.__name__}.remote()"
        )


def _validate_timeout_s(opts: Dict[str, Any]) -> None:
    """Reject a bad deadline at `.options()` time — failing at the call
    site beats failing inside the submit path."""
    t = opts.get("timeout_s")
    if t is not None:
        try:
            ok = float(t) > 0
        except (TypeError, ValueError):
            ok = False
        if not ok:
            raise ValueError(
                f"timeout_s must be a positive number of seconds, got {t!r}"
            )


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int = 1,
                 options: Optional[Dict[str, Any]] = None):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._options = options or {}

    def remote(self, *args, **kwargs):
        n = self._num_returns
        if n == 1 and self._name in getattr(
            self._handle, "_streaming_methods", ()
        ):
            n = "streaming"
        out = get_runtime().submit_actor_task(
            self._handle, self._name, list(args), kwargs,
            **{**self._options, "num_returns": n},
        )
        if n == "streaming":
            return out  # ObjectRefGenerator
        return out[0] if n == 1 else out

    def bind(self, *args, **kwargs):
        """Build a compiled-graph node instead of executing (reference:
        `dag/dag_node.py:29` DAGNode.bind)."""
        if self._options or self._num_returns != 1:
            raise ValueError(
                "per-call .options(...) are not supported on .bind(): "
                "compiled-graph nodes execute through channels, not the "
                "task path the options configure"
            )
        from ray_tpu.dag.dag_node import ClassMethodNode

        return ClassMethodNode(self._handle, self._name, args, kwargs)

    def options(self, num_returns: Optional[int] = None, **opts):
        """Per-call overrides (reference: actor method `.options()`);
        `max_retries` additionally opts the call's returns into lineage
        reconstruction (same gate as max_task_retries on the actor),
        and `timeout_s` sets an end-to-end deadline on the call (fails
        with `DeadlineExceededError` when spent, propagated into nested
        calls).  Chained calls merge, like RemoteFunction/ActorClass
        options."""
        _validate_timeout_s(opts)
        return ActorMethod(
            self._handle, self._name,
            self._num_returns if num_returns is None else num_returns,
            {**self._options, **opts},
        )


class ActorHandle:
    """Reference: `actor.py:1238` ActorHandle; callers get per-handle
    ordered delivery via process-wide sequence numbers."""

    def __init__(self, actor_id: ActorID, address, class_name: str,
                 max_task_retries: int = 0,
                 streaming_methods: Tuple[str, ...] = (),
                 method_groups: Optional[Dict[str, str]] = None):
        self._actor_id = actor_id
        self._address = address  # (node_id, worker_id)
        self._class_name = class_name
        self._max_task_retries = max_task_retries
        # method names defined as (async) generators: their calls
        # stream by default, like generator remote functions
        self._streaming_methods = tuple(streaming_methods)
        # @method(concurrency_group=...) defaults (reference: method
        # metadata in the GCS actor table)
        self._method_groups = dict(method_groups or {})

    def _next_seq(self, group: Optional[str] = None) -> int:
        from ray_tpu.core.runtime import next_actor_seq

        return next_actor_seq(self._actor_id.binary(), group)

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __reduce__(self):
        return (
            _rebuild_handle,
            (
                self._actor_id.binary(),
                self._address,
                self._class_name,
                self._max_task_retries,
                self._streaming_methods,
                self._method_groups,
            ),
        )

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()})"


def _rebuild_handle(aid_bytes, address, class_name, max_task_retries,
                    streaming_methods=(), method_groups=None):
    return ActorHandle(ActorID(aid_bytes), address, class_name,
                       max_task_retries, streaming_methods, method_groups)


class ActorClass:
    """Product of @remote on a class (reference: `actor.py:581`)."""

    def __init__(self, cls, options: Dict[str, Any]):
        self._cls = cls
        self._options = options

    def remote(self, *args, **kwargs) -> ActorHandle:
        # streaming-method discovery lives in create_actor (recorded in
        # the spec so get_actor-rebuilt handles agree with this one)
        actor_id, address, streaming, method_groups = (
            get_runtime().create_actor(
                self._cls, list(args), kwargs, **self._options
            )
        )
        return ActorHandle(
            actor_id,
            address,
            self._cls.__name__,
            self._options.get("max_task_retries", 0),
            streaming,
            method_groups,
        )

    def options(self, **opts) -> "ActorClass":
        merged = dict(self._options)
        merged.update(opts)
        return ActorClass(self._cls, merged)

    def __call__(self, *a, **k):
        raise TypeError("Actor class cannot be instantiated directly; use .remote()")


def method(**options):
    """@method decorator for actor methods (reference: `ray.method`):
    records per-method defaults — currently `concurrency_group` — that
    calls inherit unless overridden via `.options(...)`.

    @rt.remote(concurrency_groups={"io": 2})
    class A:
        @rt.method(concurrency_group="io")
        def fetch(self): ...
    """
    allowed = {"concurrency_group"}
    unknown = set(options) - allowed
    if unknown:
        raise TypeError(f"@method got unknown options {sorted(unknown)}")

    def _wrap(fn):
        fn.__rt_method_options__ = dict(options)
        return fn

    return _wrap


def remote(*args, **options):
    """@remote decorator for functions and classes (reference:
    `worker.py:3191`)."""

    def _wrap(obj):
        if isinstance(obj, type):
            return ActorClass(obj, options)
        return RemoteFunction(obj, options)

    if len(args) == 1 and not options and callable(args[0]):
        return _wrap(args[0])
    if args:
        raise TypeError("@remote accepts keyword options only")
    return _wrap


# ----------------------------------------------------------------------
# actor management
# ----------------------------------------------------------------------
def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    info = get_runtime().controller_call(
        "get_actor", {"name": name, "namespace": namespace}
    )
    if info is None or info["state"] == "DEAD":
        raise ValueError(f"no live actor named {name!r}")
    return ActorHandle(
        ActorID(info["actor_id"]),
        info["address"],
        name,
        info.get("max_task_retries", 0),
        tuple(info.get("streaming_methods", ())),
        info.get("method_groups"),
    )


def kill(handle: ActorHandle, *, no_restart: bool = True):
    get_runtime().controller_call(
        "kill_actor",
        {"actor_id": handle._actor_id.binary(), "no_restart": no_restart},
    )


def cancel(ref: ObjectRef, *, force: bool = False):
    """Cancel the task creating `ref` (reference: `ray.cancel`).
    Queued/not-yet-started tasks fail with TaskCancelledError; a task
    already executing Python code is not interrupted (the reference's
    non-force semantics)."""
    return get_runtime().cancel(ref, force=force)


# ----------------------------------------------------------------------
# cluster introspection
# ----------------------------------------------------------------------
def nodes() -> List[Dict]:
    return get_runtime().controller_call("get_nodes")


def cluster_resources() -> Dict[str, float]:
    total: Dict[str, float] = {}
    for n in nodes():
        for k, v in n["resources"].items():
            total[k] = total.get(k, 0.0) + v
    return total


def available_resources() -> Dict[str, float]:
    # controller's resource view reflects PG reservations; live
    # availability comes from per-node stats
    rt = get_runtime()
    stats = rt.noded_call("node_stats")
    return stats["available_resources"]
