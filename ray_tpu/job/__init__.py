"""Job submission: run entrypoint commands under cluster supervision.

Reference: `python/ray/dashboard/modules/job/job_manager.py` (`JobManager:58`,
`submit_job:421`) — each submitted job gets a `JobSupervisor` actor that
runs the entrypoint shell command, streams its output to a log file, and
publishes status transitions (PENDING → RUNNING → SUCCEEDED/FAILED/
STOPPED) through the control plane's KV store.
"""

from ray_tpu.job.api import (
    JobStatus,
    get_job_info,
    follow_job_logs,
    get_job_logs,
    get_job_status,
    list_jobs,
    stop_job,
    submit_job,
    wait_job,
)

__all__ = [
    "JobStatus",
    "get_job_info",
    "follow_job_logs",
    "get_job_logs",
    "get_job_status",
    "list_jobs",
    "stop_job",
    "submit_job",
    "wait_job",
]
