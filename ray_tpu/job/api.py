"""Job API + supervisor actor.

Reference: `dashboard/modules/job/job_manager.py` — the supervisor actor
(`JobSupervisor`) runs the entrypoint as a subprocess; the manager layer
here is a thin module API over the controller KV (status/metadata) and
the supervisor (logs/stop), the same split as the reference's
JobInfoStorageClient over the GCS KV.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu as rt
from ray_tpu.core.runtime import get_runtime


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


_KV_PREFIX = "job:"


def _kv_write(job_id: str, info: Dict[str, Any]):
    get_runtime().kv_put(_KV_PREFIX + job_id, json.dumps(info).encode())


def _kv_read(job_id: str) -> Optional[Dict[str, Any]]:
    raw = get_runtime().kv_get(_KV_PREFIX + job_id)
    return json.loads(raw) if raw else None


class JobSupervisor:
    """One per job (reference: `job_manager.py` JobSupervisor actor).
    Runs the entrypoint in a process group so stop() can kill the whole
    tree; output streams to a log file as it is produced."""

    def __init__(self, job_id: str, entrypoint: str, log_path: str,
                 env: Optional[Dict[str, str]] = None,
                 working_dir: Optional[str] = None):
        self._job_id = job_id
        self._entrypoint = entrypoint
        self._log_path = log_path
        self._env = env or {}
        self._cwd = working_dir
        self._proc: Optional[subprocess.Popen] = None
        self._stopped = False

    def run(self) -> str:
        """Blocking execution; returns the terminal status.  Any setup
        failure lands in the KV as FAILED — a job must never be stuck
        PENDING with no diagnostic."""
        try:
            return self._run()
        except BaseException as e:  # noqa: BLE001 — terminal status sink
            info = _kv_read(self._job_id) or {}
            info.update(status=JobStatus.FAILED, end_time=time.time(),
                        error=repr(e))
            _kv_write(self._job_id, info)
            raise
        finally:
            self._schedule_self_cleanup()

    def _run(self) -> str:
        if self._stopped:  # stop landed before the process spawned
            info = _kv_read(self._job_id) or {}
            info.update(status=JobStatus.STOPPED, end_time=time.time())
            _kv_write(self._job_id, info)
            return JobStatus.STOPPED
        info = _kv_read(self._job_id) or {}
        info.update(status=JobStatus.RUNNING, start_time=time.time())
        _kv_write(self._job_id, info)
        env = dict(os.environ)
        env.update(self._env)
        os.makedirs(os.path.dirname(self._log_path), exist_ok=True)
        with open(self._log_path, "wb") as logf:
            self._proc = subprocess.Popen(
                self._entrypoint,
                shell=True,
                stdout=logf,
                stderr=subprocess.STDOUT,
                env=env,
                cwd=self._cwd,
                start_new_session=True,  # own process group for stop()
            )
            if self._stopped:  # stop raced the spawn: kill what we made
                self.stop()
            rc = self._proc.wait()
        if self._stopped:
            status = JobStatus.STOPPED
        elif rc == 0:
            status = JobStatus.SUCCEEDED
        else:
            status = JobStatus.FAILED
        info = _kv_read(self._job_id) or {}
        info.update(status=status, end_time=time.time(), returncode=rc)
        _kv_write(self._job_id, info)
        return status

    def _schedule_self_cleanup(self):
        """Supervisors self-terminate after a linger window (long
        enough to serve logs) instead of leaking one actor per job."""
        import threading

        linger = float(os.environ.get("RT_JOB_SUPERVISOR_LINGER_S", "300"))

        def _die():
            try:
                rt_ = get_runtime()
                rt_.controller_call(
                    "kill_actor",
                    {"actor_id": rt_.actor_id.binary(), "no_restart": True},
                )
            except Exception:
                pass

        threading.Timer(linger, _die).start()

    def stop(self) -> bool:
        self._stopped = True
        if self._proc is not None and self._proc.poll() is None:
            try:
                os.killpg(os.getpgid(self._proc.pid), signal.SIGTERM)
            except ProcessLookupError:
                pass
            deadline = time.time() + 5
            while self._proc.poll() is None and time.time() < deadline:
                time.sleep(0.1)
            if self._proc.poll() is None:
                try:
                    os.killpg(os.getpgid(self._proc.pid), signal.SIGKILL)
                except ProcessLookupError:
                    pass
        return True

    def tail(self, nbytes: int = 65536) -> bytes:
        try:
            with open(self._log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - nbytes))
                return f.read()
        except OSError:
            return b""

    def read_from(self, offset: int, nbytes: int = 65536):
        """Incremental read for `rt job logs -f` (reference: the job
        SDK's tail_job_logs streaming).  Returns (chunk, new_offset)."""
        try:
            with open(self._log_path, "rb") as f:
                f.seek(offset)
                data = f.read(nbytes)
                return data, offset + len(data)
        except OSError:
            return b"", offset

    def ping(self) -> bool:
        return True


def _jobs_dir() -> str:
    base = os.environ.get("RT_TMPDIR", "/tmp/ray_tpu")
    d = os.path.join(base, "jobs")
    os.makedirs(d, exist_ok=True)
    return d


def submit_job(entrypoint: str, *, submission_id: Optional[str] = None,
               env: Optional[Dict[str, str]] = None,
               working_dir: Optional[str] = None,
               metadata: Optional[Dict[str, str]] = None) -> str:
    """Launch an entrypoint under a supervisor actor; returns the job id
    (reference: `job_manager.py:421` submit_job)."""
    job_id = submission_id or f"job_{uuid.uuid4().hex[:10]}"
    if _kv_read(job_id) is not None:
        raise ValueError(f"job {job_id!r} already exists")
    log_path = os.path.join(_jobs_dir(), f"{job_id}.log")
    _kv_write(job_id, {
        "job_id": job_id,
        "entrypoint": entrypoint,
        "status": JobStatus.PENDING,
        "submit_time": time.time(),
        "log_path": log_path,
        "metadata": metadata or {},
    })
    supervisor = (
        rt.remote(JobSupervisor)
        .options(name=f"_job_supervisor:{job_id}", max_concurrency=4,
                 num_cpus=0)
        .remote(job_id, entrypoint, log_path, env=env,
                working_dir=working_dir)
    )
    supervisor.run.remote()  # fire and track via KV
    return job_id


def get_job_info(job_id: str) -> Dict[str, Any]:
    info = _kv_read(job_id)
    if info is None:
        raise ValueError(f"no job {job_id!r}")
    return info


def get_job_status(job_id: str) -> str:
    return get_job_info(job_id)["status"]


def get_job_logs(job_id: str) -> str:
    info = get_job_info(job_id)
    try:
        sup = rt.get_actor(f"_job_supervisor:{job_id}")
        return rt.get(sup.tail.remote(), timeout=10).decode(
            "utf-8", errors="replace"
        )
    except Exception:
        # supervisor gone (past its linger window): read the file —
        # valid on the node that hosted it; elsewhere, be loud rather
        # than silently empty
        try:
            with open(info["log_path"], "rb") as f:
                return f.read().decode("utf-8", errors="replace")
        except OSError as e:
            raise RuntimeError(
                f"logs for {job_id!r} are no longer reachable (supervisor "
                f"exited; {info['log_path']} not on this node)"
            ) from e


def follow_job_logs(job_id: str, poll_s: float = 0.5):
    """Generator yielding log chunks (str) until the job reaches a
    terminal status and the log is drained — `rt job logs -f`
    (reference: JobSubmissionClient.tail_job_logs)."""
    get_job_info(job_id)
    try:
        sup = rt.get_actor(f"_job_supervisor:{job_id}")
    except Exception:
        # supervisor past its linger window: everything the job printed
        # is already on disk — same fallback as the non-follow path
        yield get_job_logs(job_id)
        return
    offset = 0

    def _file_tail_from(off: int) -> str:
        # supervisor expired mid-stream (linger timer): the log file has
        # the rest — valid on the node that hosted it
        try:
            info = get_job_info(job_id)
            with open(info["log_path"], "rb") as f:
                f.seek(off)
                return f.read().decode("utf-8", errors="replace")
        except Exception:
            return ""

    while True:
        try:
            chunk, offset = rt.get(sup.read_from.remote(offset), timeout=15)
        except Exception:
            rest = _file_tail_from(offset)
            if rest:
                yield rest
            return
        if chunk:
            yield chunk.decode("utf-8", errors="replace")
            continue  # drain fast while data is flowing
        status = get_job_status(job_id)
        if status in (JobStatus.SUCCEEDED, JobStatus.FAILED,
                      JobStatus.STOPPED):
            while True:  # drain the FULL tail, not one chunk
                try:
                    chunk, offset = rt.get(
                        sup.read_from.remote(offset), timeout=15
                    )
                except Exception:
                    rest = _file_tail_from(offset)
                    if rest:
                        yield rest
                    return
                if not chunk:
                    return
                yield chunk.decode("utf-8", errors="replace")
        time.sleep(poll_s)


def list_jobs() -> List[Dict[str, Any]]:
    rt_ = get_runtime()
    keys = rt_.controller_call("kv_keys", {"prefix": _KV_PREFIX})
    out = []
    for key in keys or []:
        raw = rt_.kv_get(key)
        if raw:
            out.append(json.loads(raw))
    return sorted(out, key=lambda j: j.get("submit_time", 0))


def stop_job(job_id: str) -> bool:
    get_job_info(job_id)
    try:
        sup = rt.get_actor(f"_job_supervisor:{job_id}")
        return rt.get(sup.stop.remote(), timeout=15)
    except ValueError:
        return False


def wait_job(job_id: str, timeout: float = 300.0) -> str:
    """Block until the job reaches a terminal status."""
    deadline = time.time() + timeout
    status = get_job_status(job_id)
    while time.time() < deadline:
        status = get_job_status(job_id)
        if status in JobStatus.TERMINAL:
            return status
        time.sleep(0.2)
    raise TimeoutError(f"job {job_id!r} still {status} after {timeout}s")
