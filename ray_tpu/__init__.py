"""ray_tpu: a TPU-native distributed computing framework.

Core runtime (tasks, actors, objects, placement groups) plus libraries
for datasets, distributed training, hyperparameter tuning, serving and
RL — designed around JAX/XLA/Pallas/pjit.  The capability contract
matches the reference Ray snapshot (see SURVEY.md); the architecture is
TPU-first: meshes and ICI topology are first-class scheduler resources,
collectives lower to `jax.lax` ops, and device arrays never ride the
host object store.
"""

from ray_tpu import exceptions
from ray_tpu.api import (
    ActorClass,
    ActorHandle,
    ObjectRefGenerator,
    RemoteFunction,
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    is_started,
    kill,
    method,
    nodes,
    put,
    remote,
    shutdown,
    wait,
)
from ray_tpu.core.object_ref import ObjectRef

__version__ = "0.1.0"


def timeline(filename=None, trace_id=None):
    """Chrome-tracing dump of recent task events merged with the
    cluster-collected trace spans (reference: `ray.timeline()`)."""
    from ray_tpu.util.state import timeline as _tl

    return _tl(filename, trace_id=trace_id)


def slo_status():
    """Per-deployment serve SLO burn rates ({app: {deployment: row}});
    see `ray_tpu.serve.slo`.  Requires a running serve controller."""
    from ray_tpu.serve.api import slo_status as _slo

    return _slo()

__all__ = [
    "ActorClass",
    "ActorHandle",
    "ObjectRef",
    "ObjectRefGenerator",
    "RemoteFunction",
    "available_resources",
    "cancel",
    "cluster_resources",
    "exceptions",
    "get",
    "get_actor",
    "init",
    "is_initialized",
    "is_started",
    "kill",
    "method",
    "nodes",
    "put",
    "remote",
    "shutdown",
    "slo_status",
    "timeline",
    "wait",
]
