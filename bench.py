"""Driver benchmarks: single-chip training throughput.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Configs (--config):
- gpt2 (default): BASELINE config #2 — GPT-2 124M pretraining
  (reference: ray/release/air_tests/air_benchmarks), 6*N FLOPs/token.
- llama_lora: BASELINE config #4 — Llama LoRA fine-tune (frozen bf16
  base + rank-8 adapters), 4*N FLOPs/token (no weight-grad matmuls
  for frozen weights).

`vs_baseline` is measured MFU divided by 0.30 — the
model-flops-utilization a tuned torch run of this size typically
reaches on the reference's GPU path — so >1.0 means the TPU-native
step beats the reference's utilization.
"""

from __future__ import annotations

import json
import time


def _run_timed(step_once, iters, *, tokens_per_iter, flops_per_token,
               metric):
    """Shared warmup + timing + MFU harness; `step_once()` runs one
    compiled train step (managing its own state) and returns the
    metrics dict.  The float() reads force device->host syncs —
    block_until_ready does NOT round-trip through the axon tunnel."""
    float(step_once()["loss"])  # warmup / compile

    t0 = time.perf_counter()
    for _ in range(iters - 1):
        step_once()
    float(step_once()["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec = tokens_per_iter * iters / dt
    mfu = tokens_per_sec * flops_per_token / _peak_flops_per_device()
    print(json.dumps({
        "metric": metric,
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.30, 4),
    }))


def _peak_flops_per_device() -> float:
    """Best-effort bf16 peak FLOP/s for the local accelerator."""
    import jax

    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu").lower()
    table = {
        "v2": 45e12,
        "v3": 123e12,
        "v4": 275e12,
        "v5 lite": 197e12,
        "v5e": 197e12,
        "v5p": 459e12,
        "v6 lite": 918e12,
        "v6e": 918e12,
    }
    for k, v in table.items():
        if k in kind:
            return v
    if "tpu" in kind:
        return 197e12
    return 1e12  # CPU: nominal, keeps the ratio finite


def bench_llama_lora() -> None:
    """BASELINE config #4 analog: Llama LoRA fine-tune step on one
    chip (reference: Ray Train Llama-2 7B LoRA, FSDP -> XLA SPMD).
    Frozen bf16 base + rank-8 LoRA adapters, flash attention, full
    remat.  On one v5e-1 (16 GB) the 7B base does not leave working
    room, so the bench runs a 1.4B-class config — the per-chip unit the
    SPMD mesh replicates; MFU is the chip-count-free comparison.
    LoRA FLOPs/token ~= 4*N (fwd 2N + activation-grad backprop 2N; no
    weight-grad matmuls for frozen weights)."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import llama

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = llama.LlamaConfig(
            vocab_size=32000, max_seq_len=1024, dim=2048, n_layers=22,
            n_heads=16, n_kv_heads=16, intermediate=5632,
            attention="flash",
        )
        batch, seq, iters = 8, 1024, 6
    else:
        cfg = llama.LlamaConfig.tiny(vocab_size=1024)
        batch, seq, iters = 2, 128, 3

    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    # the base is FROZEN: no optimizer state, no f32 master needed —
    # store it bf16 (halves base HBM and weight-read bandwidth)
    params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)
    lora = llama.init_lora(cfg, jax.random.PRNGKey(1), rank=8)
    opt = optax.adamw(2e-4)
    opt_state = opt.init(lora)
    tokens = jax.random.randint(
        jax.random.PRNGKey(2), (batch, seq + 1), 0, cfg.vocab_size,
        dtype=jnp.int32,
    )
    step = jax.jit(
        llama.make_lora_train_step(cfg, opt), donate_argnums=(1, 2)
    )
    state = {"lora": lora, "opt": opt_state}

    def step_once():
        state["lora"], state["opt"], metrics = step(
            params, state["lora"], state["opt"], tokens
        )
        return metrics

    _run_timed(
        step_once, iters, tokens_per_iter=batch * seq,
        flops_per_token=4 * llama.num_params(params),
        metric=("llama_1b4_lora_tokens_per_sec_per_chip" if on_tpu
                else "llama_lora_scaled_tokens_per_sec_cpu"),
    )


def main() -> None:
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", choices=["gpt2", "llama_lora"],
                   default="gpt2")
    args = p.parse_args()
    if args.config == "llama_lora":
        bench_llama_lora()
        return
    bench_gpt2()


def bench_gpt2() -> None:
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt2

    on_tpu = jax.default_backend() == "tpu"
    cfg = gpt2.GPT2Config.gpt2_124m()
    if on_tpu:
        # flash (Pallas) with the SINGLE-TILE FUSED backward (dq/dk/dv
        # in one kernel sharing the s/p/ds recompute + in-kernel delta)
        # + bf16 lm-head logits + full remat; batch 35 measured best
        # with the fused bwd (32: 92.3k, 34: 96.7k, 35: 98.1k,
        # 36: 95.5k tok/s on v5e-1)
        cfg = gpt2.GPT2Config(attention="flash", logits_dtype=jnp.bfloat16)
        batch, seq, iters = 35, 1024, 6
    else:  # keep CI/CPU runs under a minute; same code path
        cfg = gpt2.GPT2Config(
            vocab_size=8192, n_positions=256, n_embd=256, n_layer=4, n_head=8
        )
        batch, seq, iters = 4, 256, 3

    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    opt = gpt2.default_optimizer(total_steps=1000)
    opt_state = opt.init(params)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size, dtype=jnp.int32
    )

    step = jax.jit(gpt2.make_train_step(cfg, opt), donate_argnums=(0, 1))
    state = {"params": params, "opt": opt_state}

    def step_once():
        state["params"], state["opt"], metrics = step(
            state["params"], state["opt"], tokens
        )
        return metrics

    # 6*N FLOPs/token fwd+bwd (PaLM appendix convention, non-attn)
    _run_timed(
        step_once, iters, tokens_per_iter=batch * seq,
        flops_per_token=6 * gpt2.num_params(state["params"]),
        metric=("gpt2_124m_train_tokens_per_sec_per_chip" if on_tpu
                else "gpt2_scaled_train_tokens_per_sec_cpu"),
    )


if __name__ == "__main__":
    main()
