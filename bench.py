"""Driver benchmarks: single-chip training throughput.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Configs (--config):
- gpt2 (default): BASELINE config #2 — GPT-2 124M pretraining
  (reference: ray/release/air_tests/air_benchmarks), 6*N FLOPs/token.
- llama_lora: BASELINE config #4 — Llama LoRA fine-tune (frozen bf16
  base + rank-8 adapters), 4*N FLOPs/token (no weight-grad matmuls
  for frozen weights).
- rllib_ppo: BASELINE config #3 — RLlib PPO on the new Learner API:
  an EnvRunner fleet streaming object-plane sample refs into a pjit'd
  learner gang with async sample/train overlap (env-steps/s +
  learner updates/s; vs_baseline = overlap-on over the synchronous
  sample→update loop at the identical fleet shape).

`vs_baseline` is measured MFU divided by 0.30 — the
model-flops-utilization a tuned torch run of this size typically
reaches on the reference's GPU path — so >1.0 means the TPU-native
step beats the reference's utilization.
"""

from __future__ import annotations

import json
import time


def _run_timed(step_once, iters, *, tokens_per_iter, flops_per_token,
               metric):
    """Shared warmup + timing + MFU harness; `step_once()` runs one
    compiled train step (managing its own state) and returns the
    metrics dict.  The float() reads force device->host syncs —
    block_until_ready does NOT round-trip through the axon tunnel."""
    float(step_once()["loss"])  # warmup / compile

    t0 = time.perf_counter()
    for _ in range(iters - 1):
        step_once()
    float(step_once()["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec = tokens_per_iter * iters / dt
    mfu = tokens_per_sec * flops_per_token / _peak_flops_per_device()
    print(json.dumps({
        "metric": metric,
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.30, 4),
    }))


def _peak_flops_per_device() -> float:
    """Best-effort bf16 peak FLOP/s for the local accelerator."""
    import jax

    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu").lower()
    table = {
        "v2": 45e12,
        "v3": 123e12,
        "v4": 275e12,
        "v5 lite": 197e12,
        "v5e": 197e12,
        "v5p": 459e12,
        "v6 lite": 918e12,
        "v6e": 918e12,
    }
    for k, v in table.items():
        if k in kind:
            return v
    if "tpu" in kind:
        return 197e12
    return 1e12  # CPU: nominal, keeps the ratio finite


def bench_llama_lora() -> None:
    """BASELINE config #4 analog: Llama LoRA fine-tune step on one
    chip (reference: Ray Train Llama-2 7B LoRA, FSDP -> XLA SPMD).
    Frozen bf16 base + rank-8 LoRA adapters, flash attention, full
    remat.  On one v5e-1 (16 GB) the 7B base does not leave working
    room, so the bench runs a 1.4B-class config — the per-chip unit the
    SPMD mesh replicates; MFU is the chip-count-free comparison.
    LoRA FLOPs/token ~= 4*N (fwd 2N + activation-grad backprop 2N; no
    weight-grad matmuls for frozen weights)."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import llama

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = llama.LlamaConfig(
            vocab_size=32000, max_seq_len=1024, dim=2048, n_layers=22,
            n_heads=16, n_kv_heads=16, intermediate=5632,
            attention="flash",
        )
        batch, seq, iters = 8, 1024, 6
    else:
        cfg = llama.LlamaConfig.tiny(vocab_size=1024)
        batch, seq, iters = 2, 128, 3

    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    # the base is FROZEN: no optimizer state, no f32 master needed —
    # store it bf16 (halves base HBM and weight-read bandwidth)
    params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)
    lora = llama.init_lora(cfg, jax.random.PRNGKey(1), rank=8)
    opt = optax.adamw(2e-4)
    opt_state = opt.init(lora)
    tokens = jax.random.randint(
        jax.random.PRNGKey(2), (batch, seq + 1), 0, cfg.vocab_size,
        dtype=jnp.int32,
    )
    step = jax.jit(
        llama.make_lora_train_step(cfg, opt), donate_argnums=(1, 2)
    )
    state = {"lora": lora, "opt": opt_state}

    def step_once():
        state["lora"], state["opt"], metrics = step(
            params, state["lora"], state["opt"], tokens
        )
        return metrics

    _run_timed(
        step_once, iters, tokens_per_iter=batch * seq,
        flops_per_token=4 * llama.num_params(params),
        metric=("llama_1b4_lora_tokens_per_sec_per_chip" if on_tpu
                else "llama_lora_scaled_tokens_per_sec_cpu"),
    )


def bench_serve_llm(continuous: bool = False, replicas: int = 1,
                    decode_kernel: str = "auto", kv_dtype: str = "model",
                    weight_dtype: str = "model") -> None:
    """BASELINE config #5 analog: a Llama replica behind serve, driven
    through the FULL data plane (HTTP proxy -> pow-2 router -> replica
    -> @serve.batch -> KV-cached generate), closed-loop clients at
    three concurrency levels (reference: "Ray Serve Llama-3 8B JAX
    replica"; serve composes `pow_2_scheduler.py` + `batching.py` for
    this workload).  On a 16 GB v5e-1 the replica hosts the 1.4B-class
    per-chip unit (same argument as bench_llama_lora); bigger models
    shard over a mesh inside the replica.

    Prints one JSON line; the per-level table (tokens/s, TTFT,
    p50/p99, serve overhead vs bare in-replica `llama.generate`) goes
    to stderr and PERF.md.  vs_baseline = (serve tokens/s at the best
    level / bare generate tokens/s) / 0.85 — i.e. 1.0 means exactly
    the <=15%-overhead target for a full serving data plane; >1.0
    means the data plane costs less than that.

    `continuous=True` serves the SAME workload through the
    continuous-batching engine (`serve/llm_engine.py`, the vLLM-on-Ray
    pattern): requests join a resident decode batch mid-flight, so the
    denominator stays the gather-config's bare ceiling and vs_baseline
    directly shows the scheduling win.

    `replicas=N` (continuous mode) deploys N engine replicas behind the
    queue-depth-aware router — the scale-out axis once one replica's
    tick rate saturates a core (PERF.md: ~2,370 tok/s single-replica
    ceiling).  Concurrency levels and request counts scale with N so
    the fleet actually saturates; `vs_baseline` stays against ONE
    bare-generate replica, so N-replica aggregate shows directly as
    >1.
    """
    import concurrent.futures as cf
    import statistics
    import subprocess
    import sys
    import urllib.request

    # Probe the backend in a throwaway subprocess: the DRIVER must not
    # initialize the TPU client — the serve replica (a worker process)
    # is the chip's only owner.  RT_BENCH_PLATFORM=cpu forces the small
    # CPU config (the image's sitecustomize bakes its own JAX_PLATFORMS
    # into every interpreter, so plain env vars don't survive).
    import os

    forced = os.environ.get("RT_BENCH_PLATFORM")
    if forced:
        on_tpu = forced == "tpu"
    else:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True,
        )
        lines = [ln for ln in probe.stdout.splitlines() if ln.strip()]
        on_tpu = bool(lines) and lines[-1].strip() == "tpu"

    if replicas > 1 and not continuous:
        raise ValueError("--replicas applies to the continuous "
                         "(serve_llm_cb) config")
    if on_tpu:
        # max_batch 16 measured BEST through the full data plane even
        # though bare generate keeps scaling (B=16/32/64 -> 1847/2622/
        # 3163 tok/s): at max_batch 32 / c=64 the batcher forms ragged
        # pow-2 groups that serialize per cycle and queueing spikes
        # (measured 1425 tok/s, +34% overhead, p99 3.0 s vs 1453,
        # +5.5%, p99 0.72 s at 16) — batched-decode throughput only
        # helps serving if the batcher can actually FILL the batches.
        # The continuous engine has no such limit: slots stay full.
        model_size, prompt_len, n_new, max_batch = "llama1b4", 128, 32, 16
        levels = (1, 8, 32, 64) if continuous else (1, 8, 32)
        metric = ("serve_llama1b4_cb_tokens_per_sec" if continuous
                  else "serve_llama1b4_tokens_per_sec")
    else:
        model_size, prompt_len, n_new, max_batch = "tiny", 16, 8, 8
        levels = (1, 4, 8)
        metric = ("serve_llm_cb_tokens_per_sec_cpu" if continuous
                  else "serve_llm_tokens_per_sec_cpu")
    if replicas > 1:
        # saturation needs proportional offered load; keep the ladder's
        # lower rungs for the latency picture
        levels = tuple(c * replicas for c in levels)
        metric += f"_x{replicas}"
    engine_knobs = (decode_kernel, kv_dtype, weight_dtype)
    if engine_knobs != ("auto", "model", "model"):
        if not continuous:
            raise ValueError("--decode-kernel/--kv-dtype/--weight-dtype "
                             "apply to the continuous (serve_llm_cb) "
                             "config")
        # distinct metric names per decode/quantization variant, so
        # PERF.md rows never silently overwrite each other
        if decode_kernel != "auto":
            metric += f"_{decode_kernel}"
        if kv_dtype == "int8":
            metric += "_kv8"
        if weight_dtype == "int8":
            metric += "_w8"

    import ray_tpu as rt
    from ray_tpu import serve
    from ray_tpu.examples.serve_llm import (
        ContinuousLlamaService,
        LlamaService,
    )

    rt.init(num_workers=4, num_cpus=16)
    try:
        if continuous:
            app = ContinuousLlamaService.options(
                num_replicas=replicas, autoscaling_config=None,
                max_ongoing_requests=256,
                health_check_timeout_s=120.0,
            ).bind(model_size=model_size, max_new_tokens=n_new,
                   slots=(32 if on_tpu else 4),
                   chunk=(8 if on_tpu else 2),
                   # max_len caps ONE sequence (prompt + budget + chunk
                   # slack).  The KV cache is paged now, so this no
                   # longer taxes per-step time — but it still sizes
                   # the default pool budget (HBM)
                   max_len=prompt_len + n_new + (8 if on_tpu else 2) + 8,
                   block_size=(16 if on_tpu else 8),
                   decode_kernel=decode_kernel, kv_dtype=kv_dtype,
                   weight_dtype=weight_dtype,
                   jax_platform=(None if on_tpu else "cpu"))
        else:
            app = LlamaService.options(
                num_replicas=1, autoscaling_config=None,
                max_ongoing_requests=64, health_check_timeout_s=120.0,
            ).bind(model_size=model_size, max_new_tokens=n_new,
                   max_batch_size=max_batch,
                   jax_platform=(None if on_tpu else "cpu"))
        handle = serve.run(app, name="llm", route_prefix="/llm",
                           timeout_s=900.0)

        # Bare in-replica baseline: the no-serve ceiling the overhead
        # is computed against.  Gather mode also pre-compiles every
        # [bucket, T] shape its padded batcher can produce; the
        # continuous engine compiles its own programs on first use
        # (warmed below), so one baseline batch size suffices there.
        if continuous:
            bare_tok_s = handle.bench_direct.remote(
                max_batch, prompt_len, n_new,
                iters=(3 if on_tpu else 2),
            ).result(timeout_s=1800.0)["tokens_per_sec"]
        else:
            bare = {}
            b = 1
            while b <= max_batch:
                bare[b] = handle.bench_direct.remote(
                    b, prompt_len, n_new, iters=(3 if on_tpu else 2)
                ).result(timeout_s=1800.0)
                b *= 2
            bare_tok_s = bare[max_batch]["tokens_per_sec"]

        host, port = serve.http_address()
        url = f"http://{host}:{port}/llm"
        prompt = list(range(1, prompt_len + 1))

        def one_request(n: int = n_new) -> float:
            body = json.dumps({"tokens": [prompt],
                               "max_new_tokens": n}).encode()
            req = urllib.request.Request(url, data=body, method="POST")
            t0 = time.perf_counter()
            with urllib.request.urlopen(req, timeout=600) as r:
                out = json.loads(r.read())
            dt = time.perf_counter() - t0
            assert len(out["tokens"][0]) == n
            return dt

        # TTFT at c=1: prefill + 1 token through the full data plane
        # (its own (T, 1) shape — warm it, then measure)
        one_request(1)
        ttft = [one_request(1) for _ in range(8 if on_tpu else 3)]

        results = {}
        for c in levels:
            n_reqs = max(20, c * (10 if on_tpu else 3))
            per = n_reqs // c

            def client(_):
                return [one_request() for _ in range(per)]

            with cf.ThreadPoolExecutor(c) as pool:  # warm this level
                list(pool.map(lambda _: one_request(), range(c)))
            t0 = time.perf_counter()
            with cf.ThreadPoolExecutor(c) as pool:
                lat = [d for ds in pool.map(client, range(c)) for d in ds]
            wall = time.perf_counter() - t0
            lat.sort()
            results[c] = {
                "tokens_per_sec": len(lat) * n_new / wall,
                "p50_s": lat[len(lat) // 2],
                "p99_s": lat[min(len(lat) - 1, int(len(lat) * 0.99))],
                "requests": len(lat),
            }
            print(f"# c={c}: {results[c]['tokens_per_sec']:.0f} tok/s, "
                  f"p50 {results[c]['p50_s'] * 1e3:.0f} ms, "
                  f"p99 {results[c]['p99_s'] * 1e3:.0f} ms",
                  file=sys.stderr)

        best = max(r["tokens_per_sec"] for r in results.values())
        print(f"# bare generate (batch {max_batch}): {bare_tok_s:.0f} tok/s;"
              f" serve overhead at best level: {1 - best / bare_tok_s:+.1%};"
              f" TTFT p50 {statistics.median(ttft) * 1e3:.0f} ms",
              file=sys.stderr)
        record = {
            "metric": metric,
            "value": round(best, 2),
            "unit": "tokens/s",
            "vs_baseline": round(best / bare_tok_s / 0.85, 4),
        }
        if replicas > 1:
            record["replicas"] = replicas
            record["per_replica_tokens_per_sec"] = round(best / replicas, 2)
        print(json.dumps(record))
    finally:
        serve.shutdown()
        rt.shutdown()


def bench_rllib_ppo(num_runners: int = 8) -> None:
    """BASELINE config #3: RLlib PPO, new Learner API — the EnvRunner
    fleet shape (>=8 CPU sampling actors, vectorized envs, sample
    batches as object-plane references) feeding a >=2-device pjit
    learner gang, with async sample/train overlap.

    Env runners are numpy CPU actors by design (the reference samples
    on CPU workers too), so the learner gang runs on the host-CPU
    device mesh here — on a pod, `config.mesh` maps the same compiled
    update onto TPU devices.  `vs_baseline` is the async-overlap
    throughput over the reference's synchronous sample→update loop
    measured at the IDENTICAL fleet shape: >1.0 means the overlap
    hides sampling wall-time the sync loop pays serially.  The
    per-mode rows (overlap ratio, exactly-once accounting) go to
    stderr and PERF.md."""
    import sys

    from ray_tpu.rllib.bench import measure_rllib_ppo

    rows = measure_rllib_ppo(
        num_runners=num_runners, envs_per_runner=16, rollout_len=64,
        minibatch=2048, epochs=2, gang_devices=4, iters=4,
        compare_sync=True, include_dag=True,
    )
    a, s = rows["rllib_ppo"], rows["rllib_ppo_sync"]
    d = rows["rllib_ppo_dag"]
    for name, row in (("overlap", a), ("sync", s),
                      ("compiled-dag", d)):
        print(
            f"# {name}: {row['env_steps_per_s']:.0f} env-steps/s, "
            f"{row['updates_per_s']:.1f} updates/s, "
            f"overlap_ratio {row.get('overlap_ratio', 0.0):.2f}, "
            f"accounting_exact {row['accounting_exact']:.0f}, "
            f"runners {row['runners']:.0f}, "
            f"gang {row['gang_devices']:.0f}",
            file=sys.stderr,
        )
    assert a["accounting_exact"] == 1.0 and s["accounting_exact"] == 1.0
    assert d["accounting_exact"] == 1.0
    print(json.dumps({
        "metric": "rllib_ppo_env_steps_per_sec",
        "value": round(a["env_steps_per_s"], 2),
        "unit": "env_steps/s",
        "vs_baseline": round(
            a["env_steps_per_s"] / s["env_steps_per_s"], 4
        ),
        "learner_updates_per_sec": round(a["updates_per_s"], 2),
        "overlap_ratio": round(a["overlap_ratio"], 4),
        "num_env_runners": int(a["runners"]),
        "gang_devices": int(a["gang_devices"]),
        # compiled-DAG learner round (use_compiled_dag=True): sample
        # hop + weights broadcast over shm tensor channels.  Reported
        # as its own delta vs the RPC overlap row, win or not.
        "dag_env_steps_per_sec": round(d["env_steps_per_s"], 2),
        "dag_updates_per_sec": round(d["updates_per_s"], 2),
        "dag_overlap_ratio": round(d["overlap_ratio"], 4),
        "dag_vs_rpc_overlap": round(
            d["env_steps_per_s"] / a["env_steps_per_s"], 4
        ),
    }))


def main() -> None:
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config",
                   choices=["gpt2", "llama_lora", "serve_llm",
                            "serve_llm_cb", "rllib_ppo"],
                   default="gpt2")
    p.add_argument("--replicas", type=int, default=1,
                   help="serve_llm_cb only: deploy N engine replicas "
                        "behind the queue-depth-aware router and "
                        "saturate the fleet")
    p.add_argument("--runners", type=int, default=8,
                   help="rllib_ppo only: env-runner fleet size")
    p.add_argument("--decode-kernel", default="auto",
                   choices=["auto", "pallas", "gather"],
                   help="serve_llm_cb only: engine decode route "
                        "(auto = fused Pallas kernel on TPU, gather "
                        "elsewhere)")
    p.add_argument("--kv-dtype", default="model",
                   choices=["model", "int8"],
                   help="serve_llm_cb only: KV block-pool storage "
                        "dtype (int8 = half payload + f32 scales)")
    p.add_argument("--weight-dtype", default="model",
                   choices=["model", "int8"],
                   help="serve_llm_cb only: serve int8-quantized "
                        "weights (per-output-channel scales)")
    args = p.parse_args()
    if args.replicas > 1 and args.config != "serve_llm_cb":
        p.error("--replicas applies only to --config serve_llm_cb")
    if args.runners != 8 and args.config != "rllib_ppo":
        p.error("--runners applies only to --config rllib_ppo")
    knobs = (args.decode_kernel, args.kv_dtype, args.weight_dtype)
    if knobs != ("auto", "model", "model") and args.config != "serve_llm_cb":
        p.error("--decode-kernel/--kv-dtype/--weight-dtype apply only "
                "to --config serve_llm_cb")
    if args.config == "llama_lora":
        bench_llama_lora()
        return
    if args.config == "serve_llm":
        bench_serve_llm()
        return
    if args.config == "serve_llm_cb":
        bench_serve_llm(continuous=True, replicas=args.replicas,
                        decode_kernel=args.decode_kernel,
                        kv_dtype=args.kv_dtype,
                        weight_dtype=args.weight_dtype)
        return
    if args.config == "rllib_ppo":
        bench_rllib_ppo(num_runners=args.runners)
        return
    bench_gpt2()


def bench_gpt2() -> None:
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt2

    on_tpu = jax.default_backend() == "tpu"
    cfg = gpt2.GPT2Config.gpt2_124m()
    if on_tpu:
        # flash (Pallas) with the SINGLE-TILE FUSED backward (dq/dk/dv
        # in one kernel sharing the s/p/ds recompute + in-kernel delta)
        # + bf16 lm-head logits + full remat; batch 35 measured best
        # with the fused bwd (32: 92.3k, 34: 96.7k, 35: 98.1k,
        # 36: 95.5k tok/s on v5e-1)
        cfg = gpt2.GPT2Config(attention="flash", logits_dtype=jnp.bfloat16)
        batch, seq, iters = 35, 1024, 6
    else:  # keep CI/CPU runs under a minute; same code path
        cfg = gpt2.GPT2Config(
            vocab_size=8192, n_positions=256, n_embd=256, n_layer=4, n_head=8
        )
        batch, seq, iters = 4, 256, 3

    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    opt = gpt2.default_optimizer(total_steps=1000)
    opt_state = opt.init(params)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size, dtype=jnp.int32
    )

    step = jax.jit(gpt2.make_train_step(cfg, opt), donate_argnums=(0, 1))
    state = {"params": params, "opt": opt_state}

    def step_once():
        state["params"], state["opt"], metrics = step(
            state["params"], state["opt"], tokens
        )
        return metrics

    # 6*N FLOPs/token fwd+bwd (PaLM appendix convention, non-attn)
    _run_timed(
        step_once, iters, tokens_per_iter=batch * seq,
        flops_per_token=6 * gpt2.num_params(state["params"]),
        metric=("gpt2_124m_train_tokens_per_sec_per_chip" if on_tpu
                else "gpt2_scaled_train_tokens_per_sec_cpu"),
    )


if __name__ == "__main__":
    main()
